"""SLO & saturation observability tests (ISSUE 7): the sliding-window
quantile estimator against exact sorted-list quantiles on adversarial
streams, the scheduler time ledger's partition invariant (pure state
machine AND through a real scheduler run with faults off), SLO policy
verdicts, the perf aggregator's goodput accounting, the one-definition-site
contract between the live cost model and experiments/hbm_traffic.py, and
the perfdiff regression-gate verdict logic.

Everything except the one real-scheduler run is pure host (no engine, no
compile) — this file sits in conftest's _RUN_FIRST band of the
time-budgeted tier-1 window."""

import math
import random

import numpy as np
import pytest

from dllama_tpu.obs import instruments as ins
from dllama_tpu.obs import perf


class FakeClock:
    """Injectable monotonic clock for deterministic window/ledger tests."""

    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ------------------------------------------------------- window quantiles

ADVERSARIAL_STREAMS = {
    "sorted": list(np.linspace(1.0, 500.0, 500)),
    "reversed": list(np.linspace(500.0, 1.0, 500)),
    "constant": [7.25] * 400,
    "bimodal": [0.001] * 250 + [10.0] * 250,
    "interleaved_bimodal": [0.001, 10.0] * 250,
    "single": [42.0],
}


@pytest.mark.parametrize("name", sorted(ADVERSARIAL_STREAMS))
def test_window_quantiles_match_exact_sorted_list(name):
    """Under the per-slice cap the estimator is EXACT: every queried
    quantile equals numpy.percentile's linear-interpolation answer on the
    full stream, for every adversarial ordering."""
    stream = ADVERSARIAL_STREAMS[name]
    clk = FakeClock()
    w = perf.WindowQuantiles(window_s=60.0, slices=6, cap=1000, now_fn=clk)
    for i, v in enumerate(stream):
        w.observe(v)
        if i % 50 == 49:
            clk.advance(1.0)  # spread across slices, all inside the window
    assert w.count() == len(stream)
    for q in (0.0, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
        exact = float(np.percentile(stream, q * 100.0))
        got = w.quantile(q)
        assert got == pytest.approx(exact, rel=1e-12, abs=1e-12), (
            f"{name}: q={q} got {got} exact {exact}")
    snap = w.snapshot()
    assert snap["count"] == len(stream)
    for p, q in (("p50", 50), ("p95", 95), ("p99", 99)):
        assert snap[p] == pytest.approx(float(np.percentile(stream, q)),
                                        rel=1e-12, abs=1e-12)


def test_window_quantiles_slide_out_of_window():
    """Samples older than window_s leave the estimate: after the window
    passes, only the recent regime remains."""
    clk = FakeClock()
    w = perf.WindowQuantiles(window_s=60.0, slices=6, cap=128, now_fn=clk)
    for _ in range(100):
        w.observe(1.0)  # old regime
    clk.advance(61.0)
    for _ in range(50):
        w.observe(100.0)  # new regime, old slices expired
    assert w.count() == 50
    assert w.quantile(0.5) == pytest.approx(100.0)
    # empty window after everything expires
    clk.advance(120.0)
    assert w.count() == 0
    assert w.quantile(0.5) is None
    assert w.snapshot()["p99"] is None


def test_window_quantiles_reservoir_bounded_and_sane():
    """Past the cap the slice keeps a bounded uniform reservoir: memory
    stays <= cap per slice and the median of a known distribution stays
    close to truth (unbiased sampling, loose tolerance)."""
    random.seed(1234)
    clk = FakeClock()
    w = perf.WindowQuantiles(window_s=60.0, slices=2, cap=256, now_fn=clk)
    n = 20_000
    for i in range(n):
        w.observe(float(i % 1000))
    assert w.count() == n  # pre-reservoir count is the true count
    assert sum(len(s) for _, s, _ in w._ring) <= 2 * 256
    assert w.quantile(0.5) == pytest.approx(500.0, rel=0.15)


def test_window_quantiles_rejects_nan_and_validates_args():
    w = perf.WindowQuantiles(window_s=10.0)
    w.observe(float("nan"))
    assert w.count() == 0 and w.quantile(0.5) is None
    w.observe(3.0)
    assert w.quantile(0.0) == w.quantile(1.0) == 3.0
    with pytest.raises(ValueError):
        perf.WindowQuantiles(window_s=0.0)
    with pytest.raises(ValueError):
        perf.WindowQuantiles(cap=0)


def test_window_sums_totals_and_span():
    clk = FakeClock()
    s = perf.WindowSums(window_s=60.0, slices=6, now_fn=clk)
    s.add(tokens=5, bytes=100.0)
    clk.advance(30.0)
    s.add(tokens=7)
    t = s.totals()
    assert t == {"tokens": 12.0, "bytes": 100.0}
    # young window rates over its age, never the full window
    assert s.span_s() == pytest.approx(30.0)
    clk.advance(100.0)  # everything expires
    assert s.totals() == {}
    assert s.span_s() == pytest.approx(60.0)  # capped at the window


# ------------------------------------------------------------ time ledger


def test_time_ledger_partitions_wall_time_exactly():
    """The construction invariant, pure: every instant between start() and
    close() lands in exactly one state, so the totals sum to wall time to
    float precision — no 2% needed without a real clock."""
    clk = FakeClock()
    led = perf.TimeLedger(now_fn=clk)
    led.start("idle")
    clk.advance(1.5)
    led.transition("admission")
    clk.advance(0.25)
    led.transition("prefill")
    clk.advance(2.0)
    led.transition("decode_dispatch")
    clk.advance(0.125)
    led.transition("decode_wait")
    clk.advance(3.0)
    led.transition("emit")
    clk.advance(0.5)
    led.transition("idle")
    clk.advance(1.0)
    led.close()
    assert led.totals["idle"] == pytest.approx(2.5)
    assert led.totals["admission"] == pytest.approx(0.25)
    assert led.totals["prefill"] == pytest.approx(2.0)
    assert led.totals["decode_wait"] == pytest.approx(3.0)
    assert sum(led.totals.values()) == pytest.approx(led.wall_s())
    snap = led.snapshot()
    assert snap["covered_s"] == pytest.approx(snap["wall_s"])
    # fractions are display-rounded to 6 places; sum within that precision
    assert sum(snap["fractions"].values()) == pytest.approx(1.0, abs=1e-5)
    # closed ledger: wall frozen even as the clock runs on
    wall = led.wall_s()
    clk.advance(100.0)
    assert led.wall_s() == wall


def test_time_ledger_open_span_poke_and_reentrant_start():
    clk = FakeClock()
    led = perf.TimeLedger(now_fn=clk)
    led.start("idle")
    clk.advance(5.0)
    # snapshot bills the open span without mutating it
    assert led.snapshot()["seconds"]["idle"] == pytest.approx(5.0)
    assert led.totals["idle"] == pytest.approx(0.0)
    led.poke()  # poke DOES bill it (scrape freshness)
    assert led.totals["idle"] == pytest.approx(5.0)
    led.transition("decode_wait")
    clk.advance(1.0)
    led.close()
    wall1 = led.wall_s()
    # warm-restart re-entry: start() again accumulates, never resets
    clk.advance(2.0)  # down between close and restart — outside the ledger?
    led.start("restart_backoff")
    clk.advance(0.5)
    led.transition("idle")
    clk.advance(0.5)
    led.close()
    assert led.totals["decode_wait"] == pytest.approx(1.0)
    assert led.totals["restart_backoff"] == pytest.approx(0.5)
    assert led.wall_s() > wall1
    # NB: wall keeps counting from the FIRST start; the closed gap is the
    # only uncovered span and it reopens the partition — which is why the
    # real scheduler closes only at final worker death, not per restart
    assert led.wall_s() == pytest.approx(sum(led.totals.values()) + 2.0)


def test_time_ledger_rejects_unknown_state():
    led = perf.TimeLedger(now_fn=FakeClock())
    led.start("idle")
    with pytest.raises(ValueError, match="unknown ledger state"):
        led.transition("napping")


def test_time_ledger_feeds_the_counter_family():
    clk = FakeClock()
    led = perf.TimeLedger(counter=ins.SCHEDULER_TIME, now_fn=clk)
    base = {s: ins.SCHEDULER_TIME.labels(state=s).value()
            for s in perf.LEDGER_STATES}
    led.start("idle")
    clk.advance(2.0)
    led.transition("emit")
    clk.advance(4.0)
    led.close()
    assert (ins.SCHEDULER_TIME.labels(state="idle").value() - base["idle"]
            ) == pytest.approx(2.0)
    assert (ins.SCHEDULER_TIME.labels(state="emit").value() - base["emit"]
            ) == pytest.approx(4.0)


# ------------------------------------------------------------- SLO policy


def test_slo_policy_tristate_verdicts():
    p = perf.SloPolicy(ttft_ms=100.0, itl_ms=10.0)
    v = p.verdict(ttft_ms=80.0, itl_ms=12.5)
    assert v["ttft_ok"] is True and v["itl_ok"] is False
    assert v["ok"] is False
    assert v["violated_by_ms"] == {"ttft": None, "itl": 2.5}
    # unmeasured marks are unknowable, not violations
    v = p.verdict(ttft_ms=None, itl_ms=None)
    assert v["ttft_ok"] is None and v["itl_ok"] is None and v["ok"] is True
    # no targets configured: everything passes vacuously
    off = perf.SloPolicy()
    assert not off.enabled()
    assert off.verdict(1e9, 1e9)["ok"] is True


def test_slo_verdict_from_flight_recorder_marks():
    """The /debug/requests/{req_id} postmortem derivation: ITL from
    (e2e - ttft) / (decode_tokens - 1), same as Request.itl_ms."""
    p = perf.SloPolicy(ttft_ms=50.0, itl_ms=20.0)
    v = p.verdict_from_marks(ttft_ms=40.0, e2e_ms=400.0, decode_tokens=10)
    assert v["itl_ms"] == pytest.approx((400.0 - 40.0) / 9)
    assert v["ttft_ok"] is True and v["itl_ok"] is False
    assert v["targets"] == {"ttft_ms": 50.0, "itl_ms": 20.0}
    # a one-token request has no inter-token interval to judge
    v = p.verdict_from_marks(ttft_ms=40.0, e2e_ms=40.0, decode_tokens=1)
    assert v["itl_ok"] is None and "itl_ms" not in v


def test_perf_aggregator_goodput_vs_throughput():
    """Goodput counts only stop/length finishes inside every SLO; the
    violation burn counters move per kind."""
    clk = FakeClock()
    agg = perf.PerfAggregator(slo=perf.SloPolicy(ttft_ms=100.0, itl_ms=50.0),
                              now_fn=clk)
    base_ttft = ins.SLO_VIOLATIONS.labels(kind="ttft").value()
    base_itl = ins.SLO_VIOLATIONS.labels(kind="itl").value()
    # in-SLO success, out-of-SLO success, in-SLO error
    agg.observe_finish(finish_reason="stop", ttft_ms=50.0, itl_ms=10.0,
                       e2e_ms=500.0, tokens=40)
    agg.observe_finish(finish_reason="length", ttft_ms=500.0, itl_ms=10.0,
                       e2e_ms=900.0, tokens=40)
    agg.observe_finish(finish_reason="error", ttft_ms=50.0, itl_ms=10.0,
                       e2e_ms=100.0, tokens=40)
    clk.advance(10.0)
    assert ins.SLO_VIOLATIONS.labels(kind="ttft").value() - base_ttft == 1
    assert ins.SLO_VIOLATIONS.labels(kind="itl").value() - base_itl == 0
    slo = agg.slo_snapshot()
    assert slo["window_finished"] == 3
    assert slo["attainment"] == pytest.approx(2 / 3, abs=1e-4)
    roof = agg.roofline_snapshot()
    # 120 tokens finished, only the in-SLO stop's 40 are goodput
    assert roof["throughput_tok_s"] == pytest.approx(12.0)
    assert roof["goodput_tok_s"] == pytest.approx(4.0)
    win = agg.window_snapshot()
    assert win["ttft"]["count"] == 3 and win["ttft"]["p50"] == 50.0


def test_aggregator_prices_chunks_against_device_window():
    clk = FakeClock()
    cm = perf.ChunkCostModel(n_layers=2, dim=64, hidden_dim=128, kv_dim=32,
                             head_size=16, n_kv_heads=2, vocab_size=96,
                             seq_len=64, weight_bytes=1_000_000)
    agg = perf.PerfAggregator(cost_model=cm, now_fn=clk)
    agg.observe_chunk(occupancy=2, live_rows=10.0, steps=4, tokens=8,
                      device_s=0.25)
    roof = agg.roofline_snapshot()
    expect = cm.step_bytes(2, 10.0) * 4
    assert roof["bytes"] == expect
    # snapshot values are display-rounded (3 / 6 places)
    assert roof["achieved_gbs"] == pytest.approx(expect / 0.25 / 1e9,
                                                 abs=5e-4)
    assert roof["bandwidth_attainment"] == pytest.approx(
        (expect / 0.25) / (perf.PEAK_HBM_GBS * 1e9), abs=5e-7)
    # no cost model -> unpriced but still counted
    agg2 = perf.PerfAggregator(now_fn=clk)
    agg2.observe_chunk(occupancy=2, live_rows=10.0, steps=4, tokens=8,
                       device_s=0.25)
    r2 = agg2.roofline_snapshot()
    assert r2["priced"] is False and r2["bandwidth_attainment"] is None
    assert r2["window_chunks"] == 1


def test_cost_model_single_definition_site():
    """experiments/hbm_traffic.batched_step_bytes must price EXACTLY what
    obs/perf.decode_step_bytes prices (the offline tables and the live
    gauge share one formula — the ISSUE 7 no-drift contract)."""
    hbm = pytest.importorskip("experiments.hbm_traffic")
    cfg = hbm.PRESETS["1b"]
    for slots, frac, paged, impl in ((8, 0.5, False, "kernel"),
                                     (32, 1.0, False, "kernel"),
                                     (8, 0.25, True, "kernel"),
                                     (96, 1.0, True, "kernel"),
                                     (8, 0.25, True, "gather"),
                                     (96, 1.0, True, "gather")):
        expect = perf.decode_step_bytes(
            n_layers=cfg.n_layers, dim=cfg.dim, hidden_dim=cfg.hidden_dim,
            kv_dim=cfg.kv_dim, head_size=cfg.head_size,
            n_kv_heads=cfg.n_kv_heads, vocab_size=cfg.vocab_size,
            seq_len=cfg.seq_len, weight_bytes=hbm.q40_weight_bytes(cfg),
            slots=slots, live_rows=frac * cfg.seq_len, paged=paged,
            paged_impl=impl)
        assert hbm.batched_step_bytes(cfg, slots, live_frac=frac, paged=paged,
                                      paged_impl=impl) == expect
    assert hbm.V5E_HBM_GBS == perf.PEAK_HBM_GBS
    # the two paged routes price DIFFERENT traffic by design: the gather
    # fallback pays the re-materialized seq_len-row view (write + read, k+v,
    # per layer) the kernel route exists to remove
    kb = hbm.batched_step_bytes(cfg, 8, live_frac=0.25, paged=True,
                                paged_impl="kernel")
    gb = hbm.batched_step_bytes(cfg, 8, live_frac=0.25, paged=True,
                                paged_impl="gather")
    view = (2 * 8 * cfg.n_kv_heads * 2 * cfg.seq_len * cfg.head_size * 2
            * cfg.n_layers)
    table = 4 * 8 * (cfg.seq_len // 128) * cfg.n_layers
    assert gb - kb == view + table


# ------------------------------------------------- real-scheduler invariant


def test_scheduler_ledger_invariant_real_run():
    """ISSUE 7 acceptance: drive a REAL scheduler (tiny engine, faults off,
    default overlap) through a mixed workload and assert the ledger's
    partition invariant — per-state seconds sum to measured loop wall time
    within 2%, every state non-negative, nothing double-counted — plus the
    new tail-latency fields in latency_summary() and a populated roofline
    window."""
    import jax.numpy as jnp

    from dllama_tpu.engine.batch import BatchEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.serve.scheduler import Scheduler

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=96, seq_len=64)
    params = random_params(cfg, seed=5, dtype=jnp.float32, quantize=False)
    eng = BatchEngine(cfg, params, n_slots=3, cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=3, slo_ttft_ms=120_000.0,
                      slo_itl_ms=120_000.0)
    try:
        r1 = sched.submit([1, 2, 3], 0.0, 0.9, 10, frozenset(), seed=1)
        r2 = sched.submit([4, 5], 0.8, 0.9, 8, frozenset(), seed=2)
        assert len(list(r1.tokens())) == 10
        assert len(list(r2.tokens())) == 8
        summary = sched.latency_summary()
    finally:
        sched.shutdown()
    # shutdown joined the worker; run()'s finally closed the ledger
    led = sched.ledger.snapshot()
    assert led["state"] is None  # closed
    wall, covered = led["wall_s"], led["covered_s"]
    assert wall > 0
    assert abs(covered - wall) / wall <= 0.02, led
    assert set(led["seconds"]) == set(perf.LEDGER_STATES)
    assert all(v >= 0.0 for v in led["seconds"].values())
    # snapshot values are display-rounded to 6 places; 8 states of rounding
    assert math.fsum(led["seconds"].values()) == pytest.approx(covered,
                                                               abs=1e-5)
    # work happened: the decode path states actually accumulated time
    assert led["seconds"]["decode_wait"] > 0
    assert led["seconds"]["prefill"] > 0
    # tail-latency satellite: p50/p95 ride latency_summary now
    assert summary["ttft_ms_p50"] is not None
    assert summary["ttft_ms_p95"] >= summary["ttft_ms_p50"]
    assert summary["itl_ms_p50"] is not None
    # roofline window saw priced chunks (cost model built by the engine)
    roof = sched.perf.roofline_snapshot()
    assert roof["priced"] and roof["window_chunks"] > 0
    assert roof["bytes"] > 0 and roof["device_s"] > 0
    assert roof["bandwidth_attainment"] is not None
    # with SLO targets this loose, both requests attained
    slo = sched.perf.slo_snapshot()
    assert slo["attainment"] == 1.0


# ---------------------------------------------------------------- perfdiff


def _perfdiff():
    import experiments.perfdiff as pd
    return pd


def test_perfdiff_self_diff_always_passes():
    pd = _perfdiff()
    rec = {"value": 46.9, "slo": {"ttft_ms_p95": 120.0,
                                  "ledger_residual_frac": 0.001},
           "presets": {"tiny": {"decode_tok_s": 15.7}}}
    v = pd.diff(rec, dict(rec))
    assert v["ok"] and not v["regressions"]
    assert v["checked"] >= 3


def test_perfdiff_catches_directional_regressions():
    pd = _perfdiff()
    old = {"value": 100.0, "slo": {"ttft_ms_p95": 100.0, "agg_tok_s": 50.0}}
    # tok/s halved (higher-better) AND p95 doubled (lower-better)
    new = {"value": 50.0, "slo": {"ttft_ms_p95": 200.0, "agg_tok_s": 50.0}}
    v = pd.diff(old, new)
    assert not v["ok"]
    bad = {r["metric"] for r in v["regressions"]}
    assert bad == {"value", "slo.ttft_ms_p95"}
    # an IMPROVEMENT in each direction never fails
    better = {"value": 200.0, "slo": {"ttft_ms_p95": 10.0,
                                      "agg_tok_s": 60.0}}
    v = pd.diff(old, better)
    assert v["ok"] and len(v["improvements"]) == 3


def test_perfdiff_tolerance_and_scale():
    pd = _perfdiff()
    old = {"value": 100.0}
    within = {"value": 90.0}   # -10% < 15% tolerance
    beyond = {"value": 80.0}   # -20% > 15% tolerance
    assert pd.diff(old, within)["ok"]
    assert not pd.diff(old, beyond)["ok"]
    assert pd.diff(old, beyond, scale=2.0)["ok"]  # 30% tolerance now


def test_perfdiff_ledger_ceiling_is_absolute_and_unscaled():
    pd = _perfdiff()
    old = {"slo": {"ledger_residual_frac": 0.001}}
    ok = {"slo": {"ledger_residual_frac": 0.019}}
    bad = {"slo": {"ledger_residual_frac": 0.05}}
    assert pd.diff(old, ok)["ok"]
    assert not pd.diff(old, bad)["ok"]
    assert not pd.diff(old, bad, scale=10.0)["ok"]  # invariants don't scale


def test_perfdiff_zero_baseline_never_gates():
    """A 0.0 baseline gives relative tolerance nothing to scale by: the
    move is reported (status zero_baseline) but must not fail the gate —
    in either direction."""
    pd = _perfdiff()
    old = {"slo": {"ttft_ms_p95": 0.0}, "value": 0.0}
    new = {"slo": {"ttft_ms_p95": 125.0}, "value": 0.0}
    v = pd.diff(old, new)
    assert v["ok"] and not v["regressions"]
    assert pd.diff(old, dict(old))["ok"]  # zero -> zero self-diff


def test_perfdiff_missing_and_info_fields_never_gate():
    pd = _perfdiff()
    old = {"value": 100.0, "paged": {"tok_s_ratio_paged_dense": 0.9},
           "setup_s": 1.0}
    new = {"value": 100.0, "setup_s": 99.0}  # info field exploded: fine
    v = pd.diff(old, new)
    assert v["ok"]
    assert "paged.tok_s_ratio_paged_dense" in v["only_old"]


def test_perfdiff_accepts_real_bench_wrapper(tmp_path):
    """End-to-end through main(): the committed BENCH_r05.json self-diffs
    to PASS (exit 0) and a synthetically degraded copy FAILS (exit 1) —
    the scripts/perf_gate.sh acceptance, without the subprocess."""
    import json
    import os

    pd = _perfdiff()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "BENCH_r05.json")
    assert pd.main([src, src]) == 0
    with open(src, encoding="utf-8") as f:
        doc = json.load(f)
    doc["parsed"]["value"] *= 0.5
    degraded = tmp_path / "degraded.json"
    degraded.write_text(json.dumps(doc))
    assert pd.main([src, str(degraded)]) == 1
    assert pd.main([src, str(degraded), "--json"]) == 1
    assert pd.main(["/nonexistent.json", src]) == 2


def test_refresh_gauges_drained_window_sets_nan_not_stale():
    """After the sliding window drains, the scrape-time refresh must push
    NaN (Prometheus 'no data'), never leave the last value standing — an
    idle server does not still carry its old p95."""
    clk = FakeClock()
    agg = perf.PerfAggregator(slo=perf.SloPolicy(ttft_ms=100.0), now_fn=clk)
    agg.observe_finish(finish_reason="stop", ttft_ms=50.0, itl_ms=5.0,
                       e2e_ms=100.0, tokens=4)
    agg.refresh_gauges()
    g = ins.LATENCY_WINDOW.labels(metric="ttft", quantile="p95")
    assert g.value() == pytest.approx(0.05)
    assert ins.SLO_ATTAINMENT.value() == 1.0
    clk.advance(3600.0)  # everything leaves the window
    agg.refresh_gauges()
    assert math.isnan(g.value())
    assert math.isnan(ins.SLO_ATTAINMENT.value())
    assert math.isnan(ins.BW_ATTAINMENT.value())
    # NaN renders as the exposition grammar's NaN token, not "nan"
    from dllama_tpu.obs import metrics
    assert metrics.format_value(g.value()) == "NaN"


# ------------------------------------------------- process self-metrics


def test_process_gauges_refresh():
    got = ins.refresh_process_gauges()
    assert got["uptime_s"] >= 0.0
    assert got["threads"] >= 1
    assert got["rss_bytes"] > 0  # linux CI: /proc/self/statm exists
    assert ins.PROCESS_THREADS.value() == got["threads"]
    assert ins.PROCESS_RSS.value() == got["rss_bytes"]
