"""Prompt-lookup speculative decoding (engine/speculative.py): the invariant
is EXACTNESS — spec decode must emit the bit-identical greedy continuation
of plain decode_greedy_n for any input, while taking fewer forwards when the
text is repetitive. The reference has no speculation at all (one forward per
token, dllama.cpp:69-88); this is a capability beyond parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.engine.sampling import Sampler
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params


CFG = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=96, seq_len=160)


@pytest.fixture(scope="module")
def params():
    return random_params(CFG, seed=5, dtype=jnp.float32, quantize=False)


def _greedy_ref(params, prompt, n):
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    logits = eng.prefill(np.asarray([prompt], np.int32))
    first = int(np.argmax(np.asarray(logits)[0]))
    toks = eng.decode_greedy_n(np.array([[first]]), n)
    return first, [int(t) for t in toks[:, 0]]


def _spec(params, prompt, n, k=6, ngram=2):
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    logits = eng.prefill(np.asarray([prompt], np.int32))
    first = int(np.argmax(np.asarray(logits)[0]))
    toks = eng.decode_spec_greedy_n(list(prompt), first, n, k=k, ngram=ngram)
    return first, [int(t) for t in toks], eng._spec_stats, eng


@pytest.mark.parametrize("prompt_kind", ["repetitive", "random"])
def test_spec_matches_plain_greedy(params, prompt_kind):
    if prompt_kind == "repetitive":
        prompt = ([3, 7, 11, 19] * 8)[:30]
    else:
        prompt = list(np.random.default_rng(0).integers(1, CFG.vocab_size, 30))
    f_ref, ref = _greedy_ref(params, prompt, 24)
    f_spec, got, stats, _ = _spec(params, prompt, 24)
    assert f_ref == f_spec
    assert got == ref, f"spec diverged from greedy: {got} vs {ref}"
    assert stats["cycles"] >= 1
    # counting invariant: every cycle emits 1..k+1 tokens
    assert stats["cycles"] <= stats["emitted"] <= stats["cycles"] * 7


def test_spec_accepts_drafts_on_repetitive_text(params):
    """A strongly periodic greedy continuation must be accepted in bulk:
    fewer verify forwards than emitted tokens."""
    # drive the model into its own fixed loop first, then continue it:
    # whatever cycle greedy decode settles into IS the draftable pattern
    prompt = [5, 9, 5, 9, 5, 9, 5, 9]
    _, ref = _greedy_ref(params, prompt, 48)
    _, got, stats, _ = _spec(params, prompt, 48, k=6)
    assert got == ref
    # greedy tiny-model continuations settle into short cycles; the lookup
    # must exploit that (strictly fewer forwards than tokens)
    assert stats["cycles"] < stats["emitted"], stats


def test_spec_position_accounting_allows_continuation(params):
    """After a spec call the engine position must equal plain-greedy's, and
    further NORMAL decoding must continue the exact same stream."""
    prompt = ([2, 4, 8] * 6)[:16]
    f, ref = _greedy_ref(params, prompt, 30)
    f2, got, _, eng = _spec(params, prompt, 18, k=4)
    assert ref[:18] == got
    assert eng.pos == len(prompt) + 18
    more = eng.decode_greedy_n(np.array([[got[-1]]]), 12)
    assert [int(t) for t in more[:, 0]] == ref[18:30]


def test_spec_respects_seq_len_boundary(params):
    """Close to the context end the decoder stops early (no draft head-room
    crash) and returns what it could emit."""
    prompt = [1, 2, 3] * 10
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    logits = eng.prefill(np.asarray([prompt], np.int32))
    first = int(np.argmax(np.asarray(logits)[0]))
    room = CFG.seq_len - eng.pos
    toks = eng.decode_spec_greedy_n(list(prompt), first, room - 2, k=8)
    # while_loop exit: pos + k + 1 <= seq_len — emission may fall short of
    # the request near the wall but never overruns it
    assert eng.pos <= CFG.seq_len
    assert len(toks) <= room - 2


def test_generate_spec_stream_identical(params):
    """The public generate() loop with spec=K yields the identical token
    stream to spec=0 at temperature 0 (including chunking/rewind edges)."""
    prompt = ([3, 7, 11] * 8)[:20]

    def run(spec):
        eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
        return list(eng.generate(prompt, 33, Sampler(0.0, 0.9, 1), chunk=8,
                                 spec=spec))

    assert run(6) == run(0)


def test_spec_delta_history_multi_turn(params):
    """Chat-style reuse: turn 2 prefills only the delta and hands spec only
    the delta as history (earlier positions unknown). Must match the plain
    greedy engine fed the identical stream — and not crash on the length
    check (ADVICE-style regression for the cli chat path)."""
    t1 = [3, 7, 11] * 4
    delta = [5, 9, 5, 9]

    def turn(eng, toks, n, spec):
        logits = eng.prefill(np.asarray([toks], np.int32))
        first = int(np.argmax(np.asarray(logits)[0]))
        if spec:
            return [first] + [int(t) for t in eng.decode_spec_greedy_n(toks, first, n, k=4)]
        return [first] + [int(t) for t in eng.decode_greedy_n(np.array([[first]]), n)[:, 0]]

    eng_s = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    eng_r = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    assert turn(eng_s, t1, 8, True) == turn(eng_r, t1, 8, False)
    assert turn(eng_s, delta, 8, True) == turn(eng_r, delta, 8, False)
    assert eng_s.pos == eng_r.pos


def test_spec_honors_donate_cache_false(params):
    """donate_cache=False engines keep the caller's cache buffer alive
    through spec calls (same contract as every other jitted step)."""
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32, donate_cache=False)
    logits = eng.prefill(np.asarray([[1, 2, 3, 4]], np.int32))
    snapshot = eng.cache
    first = int(np.argmax(np.asarray(logits)[0]))
    eng.decode_spec_greedy_n([1, 2, 3, 4], first, 6, k=4)
    _ = np.asarray(snapshot.k)  # must not raise 'Array has been deleted'


def test_spec_on_tp_mesh_matches_single_device(params):
    """Speculative decoding composes with tensor parallelism: the while_loop
    carries the SHARDED cache through the engine's GSPMD fwd, and the output
    equals single-device greedy (also AOT-accepted for v5e at tp=4)."""
    from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
    from dllama_tpu.parallel.sharding import LlamaShardings

    prompt = ([3, 7, 11, 19] * 8)[:30]
    f_ref, ref = _greedy_ref(params, prompt, 16)

    mesh = make_mesh(MeshConfig(tp=2))
    sh = LlamaShardings(mesh, CFG)
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32, shardings=sh)
    logits = eng.prefill(np.asarray([prompt], np.int32))
    first = int(np.argmax(np.asarray(logits)[0]))
    toks = eng.decode_spec_greedy_n(list(prompt), first, 16, k=4)
    assert f_ref == first
    assert [int(t) for t in toks] == ref


def test_serve_spec_identical_completions(tmp_path):
    """The single-engine HTTP tier with spec=K streams the identical greedy
    completion as spec=0 (the serve wiring of --spec)."""
    import json
    import threading

    from tests.test_serve import make_tiny_files, post

    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.serve.api import make_server

    mpath, tpath, _ = make_tiny_files(tmp_path)
    body = {"messages": [{"role": "user", "content": "abc abc abc"}],
            "max_tokens": 12, "temperature": 0.0}

    def run(spec):
        loaded = load_model(mpath, tpath, mesh=None)
        httpd, api = make_server(loaded, host="127.0.0.1", port=0, spec=spec)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            status, data = post(httpd.server_address[1], "/v1/chat/completions", body)
            assert status == 200
            return json.loads(data)["choices"][0]["message"]["content"]
        finally:
            httpd.shutdown()

    assert run(6) == run(0)


def test_propose_ngram_finds_latest_match():
    from dllama_tpu.engine.speculative import propose_ngram

    h = jnp.asarray(np.array([9, 4, 7, 1, 2, 4, 7, 3, 5, 4, 7, 0, 0, 0, 0, 0],
                             np.int32))
    # sequence known up to index 10 (L=11), trailing bigram (4, 7): matches
    # end at j=2 and j=6; the LATEST (j=6) wins -> draft continues with h[7:]
    draft, found = propose_ngram(h, jnp.int32(11), k=3, ngram=2)
    assert bool(found)
    assert [int(x) for x in draft] == [3, 5, 4]


def test_propose_ngram_no_match_is_safe():
    from dllama_tpu.engine.speculative import propose_ngram

    h = jnp.asarray(np.arange(16, dtype=np.int32))
    draft, found = propose_ngram(h, jnp.int32(12), k=4, ngram=2)
    assert not bool(found)
    assert draft.shape == (4,)  # arbitrary but in-range window
