"""Contract tests for engine/kernel_select.resolve_kernels — the single
resolution point both engine tiers share (backend, shard_map wrappers, flash
gating, interpret mode). On CPU the platform branch is fixed, so these pin
the sharded/forced combinations."""

import jax.numpy as jnp
import pytest

from dllama_tpu.engine.kernel_select import resolve_kernels
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
from dllama_tpu.parallel.sharding import LlamaShardings

CFG = LlamaConfig(dim=256, hidden_dim=512, n_layers=2, n_heads=8, n_kv_heads=4,
                  vocab_size=512, seq_len=128)


def sh(spec):
    return LlamaShardings(make_mesh(MeshConfig(**spec)), CFG)


def test_unsharded_cpu_defaults_to_xla_no_flash():
    sel = resolve_kernels(CFG, 128, 1)
    assert sel.backend == "xla" and sel.mm_in is None and sel.attn_fn is None


def test_forced_pallas_unsharded_matmuls_only():
    # kernels= picks the MATMUL backend; attention stays attn_impl's choice
    sel = resolve_kernels(CFG, 128, 1, kernels="pallas")
    assert sel.backend == "pallas"
    assert sel.mm_in is None  # unsharded: plain kernels, no shard_map
    assert sel.attn_fn is None  # flash off-TPU needs attn_impl='flash'
    sel2 = resolve_kernels(CFG, 128, 1, kernels="pallas", attn_impl="flash")
    assert sel2.attn_fn is not None  # interpret-mode flash when asked


def test_forced_pallas_tp_mesh_uses_shard_map():
    sel = resolve_kernels(CFG, 128, 1, kernels="pallas", shardings=sh(dict(tp=4)))
    assert sel.backend == "pallas"
    assert sel.mm_in is not None  # in-dim-sharded matmul (psum) wrapper
    assert sel.attn_fn is not None  # head-sharded flash


def test_auto_tp_mesh_on_cpu_stays_xla():
    # auto never picks pallas off-TPU; GSPMD handles the sharded math
    sel = resolve_kernels(CFG, 128, 1, shardings=sh(dict(tp=4)))
    assert sel.backend == "xla" and sel.mm_in is None and sel.attn_fn is None


def test_sp_mesh_keeps_ring_attention_even_forced():
    sel = resolve_kernels(CFG, 128, 1, kernels="pallas", shardings=sh(dict(sp=2, tp=2)))
    assert sel.backend == "pallas"  # explicit override respected for matmuls…
    assert sel.mm_in is None  # …but NOT the shard_map tier (sp unsupported)
    assert sel.attn_fn is not None  # the sp ring attention, not flash


def test_attn_impl_jnp_disables_flash_everywhere():
    sel = resolve_kernels(CFG, 128, 1, kernels="pallas", attn_impl="jnp")
    assert sel.attn_fn is None


def test_seq_len_untileable_skips_flash():
    # flash needs cache_seq_len % 64 == 0
    sel = resolve_kernels(CFG, 96, 1, kernels="pallas")
    assert sel.backend == "pallas" and sel.attn_fn is None


# ------------------------------------------------- paged-layout routing
# (ISSUE 8): the capability check replaced the old %64 tileability gate —
# small/odd page sizes route to the fused flash-decode kernel, attn_impl=jnp
# keeps the gather fallback, and sharded meshes stay dense-only.


def test_paged_small_odd_pages_route_to_kernel():
    """Page sizes the old `paged_supported` gate rejected (8, 24) now hit
    the Pallas kernel when flash is requested (or on TPU via auto)."""
    for page in (8, 24, 128):
        sel = resolve_kernels(CFG, 128, 1, paged=True, page_size=page,
                              attn_impl="flash")
        assert sel.attn_route == "paged_kernel", page
        assert sel.attn_fn is not None and sel.attn_fn.fused_kv_scatter


def test_paged_attn_impl_jnp_keeps_gather():
    sel = resolve_kernels(CFG, 128, 1, paged=True, page_size=128,
                          attn_impl="jnp")
    assert sel.attn_route == "paged_gather" and sel.attn_fn is None


def test_paged_auto_on_cpu_keeps_gather():
    # auto never picks a Pallas path off-TPU (interpret mode is a debug
    # tool, not a serving default) — CPU serving stays on the jnp gather
    sel = resolve_kernels(CFG, 128, 1, paged=True, page_size=128)
    assert sel.attn_route == "paged_gather" and sel.attn_fn is None


def test_paged_capability_fail_falls_back_to_gather():
    # 12 rows is not sublane-aligned; f8 pools lack the Mosaic extension
    sel = resolve_kernels(CFG, 128, 1, paged=True, page_size=12,
                          attn_impl="flash")
    assert sel.attn_route == "paged_gather" and sel.attn_fn is None
    sel = resolve_kernels(CFG, 128, 1, paged=True, page_size=128,
                          attn_impl="flash", cache_dtype=jnp.float8_e4m3fn)
    assert sel.attn_route == "paged_gather" and sel.attn_fn is None


def test_paged_on_sharded_mesh_resolves_dense_only():
    """Defense in depth: BatchEngine rejects paged+mesh at construction,
    and a paged resolve over a mesh ignores the flag — the dense sharded
    rules apply (no paged route ever reaches a mesh)."""
    sel = resolve_kernels(CFG, 128, 1, kernels="pallas", paged=True,
                          page_size=128, shardings=sh(dict(tp=4)))
    assert sel.attn_route not in ("paged_kernel", "paged_gather")
    assert sel.attn_route == "sharded_flash" and sel.mm_in is not None


def test_attn_route_matches_dense_resolution():
    assert resolve_kernels(CFG, 128, 1).attn_route == "jnp"
    assert resolve_kernels(CFG, 128, 1, kernels="pallas",
                           attn_impl="flash").attn_route == "flash"
