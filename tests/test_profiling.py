"""Observability-subsystem tests (utils/profiling.py)."""

import numpy as np

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.utils import profiling


def test_token_timer_summary():
    t = profiling.TokenTimer()
    for _ in range(5):
        with t.token():
            pass
    s = t.summary()
    assert "5 tokens" in s and "tok/s" in s
    assert len(t.ms) == 5 and all(m >= 0 for m in t.ms)


def test_collective_bytes_matches_reference_scale():
    """Sanity against report.pdf Fig. 6: Llama-2-7B on 2 nodes, Q80 exchange
    ~= 1112 kB/token TOTAL (556 kB/chip). Analytic: 2 sync/layer * dim/2
    elements to 1 peer * 32 layers * ~1.06 B/elem + logits."""
    cfg = LlamaConfig(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32,
                      n_kv_heads=32, vocab_size=32000, seq_len=4096)
    est = profiling.collective_bytes_per_token(cfg, tp=2, exchange_bytes=34 / 32)
    # reference measured 1112 kB total for 2 nodes -> 556 kB/node; the
    # analytic send-side payload must land in the same regime (+-50%)
    assert 200 < est["kb_per_token_per_chip"] < 900


def test_collective_bytes_zero_single_chip():
    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=64, seq_len=32)
    assert profiling.collective_bytes_per_token(cfg, tp=1)["bytes_per_token_per_chip"] == 0


def test_memory_report(rng=np.random.default_rng(0)):
    import jax.numpy as jnp

    from dllama_tpu.models.llama import KVCache, random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=64, seq_len=32)
    params = random_params(cfg, dtype=jnp.bfloat16, quantize=False)
    cache = KVCache.create(cfg, 1)
    rep = profiling.memory_report(cfg, params, cache)
    assert "params" in rep and "kv-cache" in rep and "GB" in rep
