"""Observability-subsystem tests (utils/profiling.py)."""

import numpy as np

from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.utils import profiling


def test_token_timer_summary():
    t = profiling.TokenTimer()
    for _ in range(5):
        with t.token():
            pass
    s = t.summary()
    assert "5 tokens" in s and "tok/s" in s
    assert len(t.ms) == 5 and all(m >= 0 for m in t.ms)


def test_collective_bytes_matches_reference_scale():
    """Sanity against report.pdf Fig. 6: Llama-2-7B on 2 nodes, Q80 exchange
    ~= 1112 kB/token TOTAL (556 kB/chip). Analytic: 2 sync/layer * dim/2
    elements to 1 peer * 32 layers * ~1.06 B/elem + logits."""
    cfg = LlamaConfig(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32,
                      n_kv_heads=32, vocab_size=32000, seq_len=4096)
    est = profiling.collective_bytes_per_token(cfg, tp=2, exchange_bytes=34 / 32)
    # reference measured 1112 kB total for 2 nodes -> 556 kB/node; the
    # analytic send-side payload must land in the same regime (+-50%)
    assert 200 < est["kb_per_token_per_chip"] < 900


def test_collective_bytes_zero_single_chip():
    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=64, seq_len=32)
    assert profiling.collective_bytes_per_token(cfg, tp=1)["bytes_per_token_per_chip"] == 0


def test_memory_report(rng=np.random.default_rng(0)):
    import jax.numpy as jnp

    from dllama_tpu.models.llama import KVCache, random_params

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=64, seq_len=32)
    params = random_params(cfg, dtype=jnp.bfloat16, quantize=False)
    cache = KVCache.create(cfg, 1)
    rep = profiling.memory_report(cfg, params, cache)
    assert "params" in rep and "kv-cache" in rep and "GB" in rep


def test_measured_collective_bytes_tp_step():
    """The compiled tp=4 decode step must contain real collectives whose
    summed bytes are nonzero; the unsharded step must contain none."""
    import jax.numpy as jnp

    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.models.config import LlamaConfig
    from dllama_tpu.models.llama import random_params
    from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
    from dllama_tpu.parallel.sharding import LlamaShardings

    cfg = LlamaConfig(dim=128, hidden_dim=256, n_layers=2, n_heads=4, n_kv_heads=4,
                      vocab_size=256, seq_len=32)
    params = random_params(cfg, seed=0, dtype=jnp.float32, quantize=True)

    solo = InferenceEngine(cfg, params, cache_dtype=jnp.float32)
    assert solo.measured_collective_report()["total_bytes"] == 0

    mesh = make_mesh(MeshConfig(tp=4))
    sh = LlamaShardings(mesh, cfg)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.float32, shardings=sh)
    meas = eng.measured_collective_report()
    assert meas["total_bytes"] > 0
    assert meas["per_op"]  # at least one collective kind identified


def test_measured_collective_bytes_parser():
    from dllama_tpu.utils import profiling

    text = """
  %ar = bf16[1,2048]{1,0:T(8,128)} all-reduce(bf16[1,2048]{1,0} %x), replica_groups={}
  %ags = (f32[256]{0}, f32[1024]{0:T(8)S(1)}) all-gather-start(f32[256]{0} %y), dimensions={0}
  %agd = f32[1024]{0} all-gather-done((f32[256]{0}, f32[1024]{0}) %ags)
  %other = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)
"""
    out = profiling.measured_collective_bytes(text)
    assert out["per_op"]["all-reduce"] == 2048 * 2  # TPU tiled layout spanned
    assert out["per_op"]["all-gather"] == 1024 * 4  # -start input alias skipped
    assert "all-gather-done" not in out["per_op"]
    assert out["total_bytes"] == 2048 * 2 + 1024 * 4
