"""Ring / sequence-parallel attention correctness on the 8-device CPU mesh.

The capability the reference lacks outright (SURVEY.md §5.7): KV sequence
sharding. Every test compares against the single-device full-softmax
reference with tight tolerances (exact math, only reduction-order noise)."""

import math

import numpy as np
import pytest

import jax

from dllama_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.ops.layers import gqa_attention
from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
from dllama_tpu.parallel.ring_attention import ring_attention, sp_cache_attention
from dllama_tpu.parallel.sharding import LlamaShardings


def full_causal_reference(q, k, v):
    """Plain causal GQA softmax in f64-ish f32, query i attends keys <= i."""
    b, t, hq, d = q.shape
    hkv = k.shape[1]
    g = hq // hkv
    qg = q.reshape(b, t, hkv, g, d).astype(np.float32)
    s = np.einsum("bthgd,bhsd->bhgts", qg, k.astype(np.float32)) / math.sqrt(d)
    mask = np.tril(np.ones((t, t), bool))
    s = np.where(mask[None, None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    o = np.einsum("bhgts,bhsd->bhgtd", p, v.astype(np.float32))
    return o.transpose(0, 3, 1, 2, 4).reshape(b, t, hq, d)


@pytest.mark.parametrize("sp,hq,hkv", [(8, 4, 4), (4, 8, 2), (2, 4, 2)])
def test_ring_attention_matches_full_causal(rng, sp, hq, hkv):
    b, t, d = 2, 64, 16
    q = rng.standard_normal((b, t, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, hkv, t, d)).astype(np.float32)
    v = rng.standard_normal((b, hkv, t, d)).astype(np.float32)
    want = full_causal_reference(q, k, v)

    mesh = make_mesh(MeshConfig(sp=sp))
    got = jax.jit(
        _shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, "sp", None, None), P(None, None, "sp", None), P(None, None, "sp", None)),
            out_specs=P(None, "sp", None, None),
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=1e-4)


def test_ring_attention_non_causal(rng):
    b, t, hq, hkv, d = 1, 32, 4, 2, 8
    q = rng.standard_normal((b, t, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, hkv, t, d)).astype(np.float32)
    v = rng.standard_normal((b, hkv, t, d)).astype(np.float32)
    g = hq // hkv
    qg = q.reshape(b, t, hkv, g, d)
    s = np.einsum("bthgd,bhsd->bhgts", qg, k) / math.sqrt(d)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhgts,bhsd->bhgtd", p, v).transpose(0, 3, 1, 2, 4).reshape(b, t, hq, d)

    mesh = make_mesh(MeshConfig(sp=4))
    got = jax.jit(
        _shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=False),
            mesh=mesh,
            in_specs=(P(None, "sp", None, None), P(None, None, "sp", None), P(None, None, "sp", None)),
            out_specs=P(None, "sp", None, None),
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("t,pos", [(1, 17), (4, 8), (8, 0)])
def test_sp_cache_attention_matches_gqa(rng, t, pos):
    """LSE-merge sharded-cache attention == full-cache gqa_attention for
    decode (t=1) and chunked prefill (t>1) at arbitrary positions."""
    b, hq, hkv, d, s = 2, 8, 4, 16, 32
    q = rng.standard_normal((b, t, hq, d)).astype(np.float32)
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    want = np.asarray(gqa_attention(jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), jnp.int32(pos)))

    mesh = make_mesh(MeshConfig(sp=4, tp=2))
    got = jax.jit(
        _shard_map(
            lambda q, kc, vc, p: sp_cache_attention(q, kc, vc, p, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, None, "tp", None), P(None, "tp", "sp", None), P(None, "tp", "sp", None), P()),
            out_specs=P(None, None, "tp", None),
        )
    )(q, kc, vc, jnp.int32(pos))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=1e-4)


def test_engine_sp_shard_map_end_to_end():
    """Engine with sp>1 now routes attention through the shard_map LSE path;
    must equal the single-device engine bit-for-tolerance."""
    cfg = LlamaConfig(
        dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4, vocab_size=128, seq_len=64
    )
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)
    prompt = np.array([[5, 9, 2, 7, 1, 3]], dtype=np.int32)

    ref = InferenceEngine(cfg, params, cache_dtype=jnp.float32)
    ref_logits = np.asarray(ref.prefill(prompt))
    ref_l2 = np.asarray(ref.decode_step(np.array([[11]])))

    mesh = make_mesh(MeshConfig(sp=4, tp=2))
    sh = LlamaShardings(mesh, cfg)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.float32, shardings=sh)
    assert sh.attn_fn(1) is not None
    got = np.asarray(eng.prefill(prompt))
    np.testing.assert_allclose(got, ref_logits, atol=2e-4, rtol=1e-3)
    got_l2 = np.asarray(eng.decode_step(np.array([[11]])))
    np.testing.assert_allclose(got_l2, ref_l2, atol=2e-4, rtol=1e-3)


@pytest.mark.parametrize("t,pos", [(8, 0), (8, 8), (16, 16)])
def test_ring_cache_attention_matches_gqa(rng, t, pos):
    """Sequence-sharded-query prefill over the rotating cache == full-cache
    gqa_attention at arbitrary chunk positions (VERDICT r1 #6)."""
    from dllama_tpu.parallel.ring_attention import ring_cache_attention

    b, hq, hkv, d, s = 2, 8, 4, 16, 32
    q = rng.standard_normal((b, t, hq, d)).astype(np.float32)
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    want = np.asarray(gqa_attention(jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), jnp.int32(pos)))

    mesh = make_mesh(MeshConfig(sp=4, tp=2))
    got = jax.jit(
        _shard_map(
            lambda q, kc, vc, p: ring_cache_attention(q, kc, vc, p, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, "sp", "tp", None), P(None, "tp", "sp", None), P(None, "tp", "sp", None), P()),
            out_specs=P(None, "sp", "tp", None),
        )
    )(q, kc, vc, jnp.int32(pos))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("sp,tp", [(2, 1), (4, 2), (8, 1)])
def test_engine_sp_ring_prefill_long_prompt(sp, tp):
    """e2e: a prompt longer than one sp shard's cache slice prefills through
    the ring path (chunk width divisible by sp -> ring_cache_attention) and
    matches single-device logits; decode then runs the LSE-merge path."""
    cfg = LlamaConfig(
        dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4, vocab_size=128, seq_len=64
    )
    params = random_params(cfg, seed=3, dtype=jnp.float32, quantize=False)
    rng = np.random.default_rng(11)
    # seq_len/sp <= 32 for sp>=2; prompt of 40 spans multiple shard slices
    prompt = rng.integers(1, cfg.vocab_size, size=(1, 40)).astype(np.int32)

    ref = InferenceEngine(cfg, params, cache_dtype=jnp.float32)
    ref_logits = np.asarray(ref.prefill(prompt))
    ref_l2 = np.asarray(ref.decode_step(np.array([[11]])))

    mesh = make_mesh(MeshConfig(sp=sp, tp=tp))
    sh = LlamaShardings(mesh, cfg)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.float32, shardings=sh)
    got = np.asarray(eng.prefill(prompt))
    np.testing.assert_allclose(got, ref_logits, atol=2e-4, rtol=1e-3)
    got_l2 = np.asarray(eng.decode_step(np.array([[11]])))
    np.testing.assert_allclose(got_l2, ref_l2, atol=2e-4, rtol=1e-3)
