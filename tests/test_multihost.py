"""Multi-host plumbing tests (single-process here; the wrappers must be
correct pass-throughs and the sharded-put fallback exact)."""

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.parallel import multihost


def test_initialize_arg_passthrough(monkeypatch):
    calls = {}
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: calls.update(kw))
    multihost.initialize("10.0.0.1:1234", 4, 2)
    assert calls == {"coordinator_address": "10.0.0.1:1234", "num_processes": 4, "process_id": 2}
    calls.clear()
    multihost.initialize()  # TPU-pod metadata path: no explicit args
    assert calls == {}


def test_device_put_sharded_single_process():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    x = np.arange(16, dtype=np.float32).reshape(2, 8)
    y = multihost.device_put_sharded(x, NamedSharding(mesh, P(None, "tp")))
    np.testing.assert_array_equal(np.asarray(y), x)
    assert len(y.addressable_shards) == 2


def test_device_put_sharded_callback_path(monkeypatch):
    """Force the multi-process branch: every addressable shard must be cut
    from the host copy by index."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
    x = np.arange(32, dtype=np.float32).reshape(4, 8)
    y = multihost.device_put_sharded(x, NamedSharding(mesh, P("tp", None)))
    np.testing.assert_array_equal(np.asarray(y), x)
    assert len(y.addressable_shards) == 4
