"""Overlapped decode pipeline tests (ISSUE 3): device-resident decode state,
overlap-on vs overlap-off stream parity, the one-chunk EOS-overrun rewind,
and the per-slot chunk clamp at the cache edge.

The parity contract: with fixed prompts/seeds/chunk, --overlap on and off
produce BIT-IDENTICAL token streams — overlap changes only when host work
runs relative to device compute, never what the device computes."""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.batch import BatchEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.serve.scheduler import Scheduler

CFG = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=96, seq_len=64)
PARAMS = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)


def _make_sched(overlap, n_slots=3, chunk=3, spec=0, seq_len=None):
    eng = BatchEngine(CFG, PARAMS, n_slots=n_slots, cache_dtype=jnp.float32,
                      spec=spec, max_seq_len=seq_len)
    return Scheduler(eng, chunk=chunk, overlap=overlap)


_WORKLOADS: dict = {}


def _run_workload(overlap, spec=0):
    """Mixed workload: greedy, sampled, and penalized requests with staggered
    submission; returns every stream + finish reason. Memoized per
    (overlap, spec): several parity tests compare the same runs, and each
    one costs an engine compile inside the time-budgeted tier-1 window."""
    key = (overlap, spec)
    if key in _WORKLOADS:
        return _WORKLOADS[key]
    sched = _make_sched(overlap, spec=spec)
    try:
        r1 = sched.submit([1, 2, 3, 1, 2, 3], 0.0, 0.9, 12, frozenset(), seed=1)
        it1 = r1.tokens()
        head = [next(it1), next(it1)]  # r1 decodes before the others join
        r2 = sched.submit([9, 8, 7], 1.1, 0.9, 10, frozenset(), seed=42)
        r3 = sched.submit([4, 5], 0.9, 0.8, 8, frozenset(), seed=7,
                          presence=0.5, frequency=0.3)
        out2 = list(r2.tokens())
        out3 = list(r3.tokens())
        out1 = head + list(it1)
        _WORKLOADS[key] = [(out1, r1.finish_reason), (out2, r2.finish_reason),
                           (out3, r3.finish_reason)]
        return _WORKLOADS[key]
    finally:
        sched.shutdown()


def test_overlap_parity_mixed_batch():
    """Greedy + sampled + penalized requests: identical streams and finish
    reasons with overlap on vs off."""
    assert _run_workload(True) == _run_workload(False)


def test_overlap_parity_with_spec():
    """A spec engine runs lockstep internally (spec cycles are consumed in
    place), but the overlap=True scheduler must still match overlap=False
    exactly, spec or not."""
    on_spec = _run_workload(True, spec=4)
    assert on_spec == _run_workload(False, spec=4)
    assert on_spec == _run_workload(True, spec=0)


def test_overlap_parity_eos_stops():
    """Token-level EOS stops mid-stream: same tokens either way, and the
    stream ends exactly at the EOS token."""

    def run(overlap):
        sched = _make_sched(overlap, chunk=4)
        try:
            probe = sched.submit([4, 5], 0.0, 0.9, 12, frozenset(), seed=0)
            ref = list(probe.tokens())
            eos = ref[3]  # stop on the 4th emitted token
            req = sched.submit([4, 5], 0.0, 0.9, 40, frozenset([eos]), seed=0)
            return ref, list(req.tokens()), req.finish_reason
        finally:
            sched.shutdown()

    on, off = run(True), run(False)
    assert on == off
    ref, got, fin = on
    stop_at = ref.index(ref[3]) + 1
    assert got == ref[:stop_at] and fin == "stop"


def test_eos_overrun_rewinds_to_emitted_prefix():
    """The overrun contract: an EOS found while the next chunk is already in
    flight discards the overrun tokens, and keep_rows/slot_tokens record
    ONLY the truly-emitted prefix — so a follow-up prompt reuses exactly
    those rows and the prefix cache never serves overrun rows."""
    sched = _make_sched(True, n_slots=2, chunk=4)
    try:
        probe = sched.submit([7, 8, 9], 0.0, 0.9, 10, frozenset(), seed=0)
        ref = list(probe.tokens())
        eos = ref[2]
        assert eos not in ref[:2]  # the stop really is the 3rd token
        prompt = [7, 8, 9]
        req = sched.submit(prompt, 0.0, 0.9, 40, frozenset([eos]), seed=0)
        got = list(req.tokens())
        assert got == ref[:3] and req.finish_reason == "stop"
        slot = [s for s, t in sched.slot_tokens.items() if t][0]
        # the last emitted token (the EOS) was sampled but never fed back:
        # exactly len(prompt) + len(got) - 1 rows are live
        assert sched.slot_tokens[slot] == prompt + got[:-1]
        assert int(sched.engine.pos[slot]) == len(prompt) + len(got) - 1

        # …and a follow-up extending the stream reuses exactly that prefix
        # (reused_prefix_tokens is cumulative — earlier admissions may have
        # reused the probe's rows too, so assert the delta)
        before = sched.reused_prefix_tokens
        follow = prompt + got + [11, 12]
        r2 = sched.submit(follow, 0.0, 0.9, 6, frozenset(), seed=5)
        warm = list(r2.tokens())
        assert sched.reused_prefix_tokens - before == len(prompt) + len(got) - 1
    finally:
        sched.shutdown()

    cold_sched = _make_sched(True, n_slots=2, chunk=4)
    try:
        r3 = cold_sched.submit(follow, 0.0, 0.9, 6, frozenset(), seed=5)
        assert list(r3.tokens()) == warm, "reused overrun rows changed output"
    finally:
        cold_sched.shutdown()


def test_host_gap_recorded_and_near_zero_under_overlap():
    """Both modes record inter-chunk host gaps; the summary fields exist and
    are sane (the on-vs-off magnitude comparison is the bench's job — CPU CI
    timing is too noisy for a threshold here)."""
    for overlap in (True, False):
        sched = _make_sched(overlap, chunk=2)
        try:
            req = sched.submit([1, 2, 3], 0.0, 0.9, 10, frozenset(), seed=0)
            list(req.tokens())
            s = sched.latency_summary()
            assert s["decode_host_gaps"] >= 1
            assert s["decode_host_gap_ms_mean"] is not None
            assert s["decode_host_gap_ms_mean"] >= 0.0
        finally:
            sched.shutdown()


# ------------------------------------------------- per-slot chunk clamp fix


def test_decode_chunk_not_clamped_by_full_slot():
    """Regression (ISSUE 3 satellite): one slot near seq_len used to shrink
    EVERY batch-mate's chunk to its room (then error at room<=0). Now the
    full slot freezes per-row at the cache edge while others keep full
    chunks."""
    seq_len = CFG.seq_len  # 64
    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    solo = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)

    near = list(range(1, seq_len - 2))  # pos = 61 after prefill: room 3
    be.add(0, near, temperature=0.0, seed=0)
    be.add(1, [1, 2, 3], temperature=0.0, seed=1)
    solo.add(1, [1, 2, 3], temperature=0.0, seed=1)

    toks = be.decode(6)  # old code: clamped to 3 for BOTH slots
    want = solo.decode(6)
    assert toks.shape[0] == 6
    np.testing.assert_array_equal(toks[:, 1], want[:, 1])
    assert int(be.pos[0]) == seq_len  # froze exactly at the edge
    assert int(be.pos[1]) == 3 + 6
    # the frozen slot's trailing tokens repeat its last real token
    assert toks[3, 0] == toks[4, 0] == toks[5, 0]

    # old code: room<=0 raised even though slot 1 had space — now the full
    # slot just stays frozen and batch-mates decode on
    toks2 = be.decode(4)
    want2 = solo.decode(4)
    np.testing.assert_array_equal(toks2[:, 1], want2[:, 1])
    assert int(be.pos[0]) == seq_len
    # only when EVERY active slot is at the edge does decode refuse
    be.release(1)
    with pytest.raises(ValueError, match="seq_len"):
        be.decode(2)


def test_scheduler_finishes_full_slot_while_others_decode():
    """Scheduler-level: a request that runs into seq_len finishes with
    'length' without shrinking its batch-mate's chunks, overlap on and off
    agreeing exactly."""

    def run(overlap):
        sched = _make_sched(overlap, n_slots=2, chunk=4)
        try:
            long_req = sched.submit(list(range(1, CFG.seq_len - 3)), 0.0, 0.9,
                                    40, frozenset(), seed=2)
            short = sched.submit([5, 6, 7], 0.0, 0.9, 20, frozenset(), seed=3)
            out_l = list(long_req.tokens())
            out_s = list(short.tokens())
            return out_l, long_req.finish_reason, out_s, short.finish_reason
        finally:
            sched.shutdown()

    on, off = run(True), run(False)
    assert on == off
    out_l, fin_l, out_s, fin_s = on
    # room 4 from pos 60: the commit's first token + 4 decoded rows
    assert fin_l == "length" and len(out_l) == 5
    assert fin_s == "length" and len(out_s) == 20  # full budget, full chunks
