"""Sparse-MoE tests: routing math vs a per-token reference loop, `.m` format
roundtrip, and expert-parallel ('ep') sharded execution on the virtual mesh.

The reference parses N_EXPERTS/N_ACTIVE_EXPERTS from the header (llm.hpp:17-18)
and its converter writes expert tensors, but buildLlmNet has no MoE path
(SURVEY.md §2.4) — these tests cover the capability it never shipped.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models import formats
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, forward, random_params
from dllama_tpu.ops.layers import build_rope_cache, moe_ffn
from dllama_tpu.ops.quant import FloatType


def moe_cfg(weight_type=FloatType.F32, experts=4, active=2):
    return LlamaConfig(dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
                       vocab_size=128, seq_len=32, n_experts=experts,
                       n_active_experts=active, weight_type=weight_type)


def naive_moe(h, gate, w1, w2, w3, k):
    """Per-token loop reference: route, run only the chosen experts, combine."""
    b, t, d = h.shape
    out = np.zeros_like(h, dtype=np.float64)
    for bi in range(b):
        for ti in range(t):
            x = h[bi, ti]
            logits = x @ gate  # [E]
            top = np.argsort(-logits)[:k]
            p = np.exp(logits[top] - logits[top].max())
            p /= p.sum()
            for w, e in zip(p, top):
                g = x @ w1[e]
                u = x @ w3[e]
                silu = g / (1.0 + np.exp(-g)) * u
                out[bi, ti] += w * (silu @ w2[e])
    return out


def test_moe_ffn_matches_naive_loop(rng):
    cfg = moe_cfg()
    b, t = 2, 3
    h = rng.standard_normal((b, t, cfg.dim)).astype(np.float32)
    gate = rng.standard_normal((cfg.dim, cfg.n_experts)).astype(np.float32)
    w1 = rng.standard_normal((cfg.n_experts, cfg.dim, cfg.hidden_dim)).astype(np.float32) * 0.1
    w2 = rng.standard_normal((cfg.n_experts, cfg.hidden_dim, cfg.dim)).astype(np.float32) * 0.1
    w3 = rng.standard_normal((cfg.n_experts, cfg.dim, cfg.hidden_dim)).astype(np.float32) * 0.1

    got = moe_ffn(cfg, jnp.asarray(h), jnp.asarray(gate), jnp.asarray(w1),
                  jnp.asarray(w2), jnp.asarray(w3))
    want = naive_moe(h, gate, w1, w2, w3, cfg.n_active_experts)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_moe_sort_grouped_gemm_matches_naive_loop(rng):
    """The sort/ragged_dot scheme (MegaBlocks-style grouped GEMM, VERDICT r3
    #6) is exact: no capacity drops, so it must match the per-token loop as
    tightly as dense does."""
    cfg = moe_cfg()
    b, t = 2, 5
    h = rng.standard_normal((b, t, cfg.dim)).astype(np.float32)
    gate = rng.standard_normal((cfg.dim, cfg.n_experts)).astype(np.float32)
    w1 = rng.standard_normal((cfg.n_experts, cfg.dim, cfg.hidden_dim)).astype(np.float32) * 0.1
    w2 = rng.standard_normal((cfg.n_experts, cfg.hidden_dim, cfg.dim)).astype(np.float32) * 0.1
    w3 = rng.standard_normal((cfg.n_experts, cfg.dim, cfg.hidden_dim)).astype(np.float32) * 0.1

    got = moe_ffn(cfg, jnp.asarray(h), jnp.asarray(gate), jnp.asarray(w1),
                  jnp.asarray(w2), jnp.asarray(w3), impl="sort")
    want = naive_moe(h, gate, w1, w2, w3, cfg.n_active_experts)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_moe_sort_matches_dense_at_scale(rng):
    """sort and dense agree on a bigger batch (every expert segment size
    exercised, including empty segments when routing is skewed)."""
    cfg = moe_cfg(experts=6, active=2)
    h = jnp.asarray(rng.standard_normal((2, 16, cfg.dim)), jnp.float32)
    gate_np = rng.standard_normal((cfg.dim, 6)).astype(np.float32)
    # skew the router so at least one expert gets (almost) no tokens
    gate_np[:, -1] -= 10.0
    gate = jnp.asarray(gate_np)
    ws = [jnp.asarray(rng.standard_normal(s), jnp.float32) * 0.1
          for s in [(6, cfg.dim, cfg.hidden_dim), (6, cfg.hidden_dim, cfg.dim),
                    (6, cfg.dim, cfg.hidden_dim)]]
    got = np.asarray(moe_ffn(cfg, h, gate, *ws, impl="sort"))
    want = np.asarray(moe_ffn(cfg, h, gate, *ws, impl="dense"))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_moe_top1_selects_single_expert(rng):
    """top-1 routing must equal the argmax expert's SwiGLU output exactly
    (softmax over one logit == 1)."""
    cfg = moe_cfg(experts=3, active=1)
    h = jnp.asarray(rng.standard_normal((1, 2, cfg.dim)), jnp.float32)
    gate = jnp.asarray(rng.standard_normal((cfg.dim, 3)), jnp.float32)
    ws = [jnp.asarray(rng.standard_normal(s), jnp.float32) * 0.1
          for s in [(3, cfg.dim, cfg.hidden_dim), (3, cfg.hidden_dim, cfg.dim),
                    (3, cfg.dim, cfg.hidden_dim)]]
    got = np.asarray(moe_ffn(cfg, h, gate, *ws))
    for ti in range(2):
        x = np.asarray(h)[0, ti]
        e = int(np.argmax(x @ np.asarray(gate)))
        g = x @ np.asarray(ws[0])[e]
        u = x @ np.asarray(ws[2])[e]
        want = (g / (1 + np.exp(-g)) * u) @ np.asarray(ws[1])[e]
        np.testing.assert_allclose(got[0, ti], want, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("wt", [FloatType.F32, FloatType.Q40])
def test_moe_format_roundtrip_forward(tmp_path, rng, wt):
    """save_model -> load_params -> forward must equal forward on params built
    directly from the same tensors (loader mapping: transposes + expert stack)."""
    cfg = moe_cfg(weight_type=wt)
    plan = formats.tensor_plan(cfg)
    names = [n for n, _, _ in plan]
    assert any("moe_gate" in n for n in names) and not any(".w1" in n for n in names)
    tensors = {n: (rng.standard_normal(s) * 0.1).astype(np.float32) for n, s, _ in plan}
    path = str(tmp_path / "moe.m")
    formats.save_model(path, cfg, tensors)

    cfg2, hs = formats.read_header(path)
    assert cfg2.n_experts == cfg.n_experts and cfg2.n_active_experts == cfg.n_active_experts
    params = formats.load_params(path, cfg2, hs, dtype=jnp.float32)

    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 4)), jnp.int32)
    rope = build_rope_cache(cfg)
    logits, _ = forward(cfg, params, toks, jnp.int32(0), KVCache.create(cfg, 1, jnp.float32), rope)
    assert logits.shape == (1, 4, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))

    if wt == FloatType.F32:
        # exact parity against directly-constructed params
        direct = {
            "embedding": jnp.asarray(tensors["embedding"]),
            "final_norm": jnp.asarray(tensors["final_norm"]),
            "wcls": jnp.asarray(tensors["wcls"].T.copy()),
            "layers": {},
        }
        L = cfg.n_layers
        stack = lambda short, tr: jnp.stack(
            [jnp.asarray(tr(tensors[f"layers.{l}.{short}"])) for l in range(L)], 0
        )
        for short in ("wq", "wk", "wv", "wo"):
            direct["layers"][short] = stack(short, lambda x: x.T.copy())
        for short in ("rms_att", "rms_ffn"):
            direct["layers"][short] = stack(short, lambda x: x)
        direct["layers"]["moe_gate"] = stack("moe_gate", lambda x: x.T.copy())
        for short in ("moe_w1", "moe_w2", "moe_w3"):
            direct["layers"][short] = stack(short, lambda x: np.swapaxes(x, 1, 2).copy())
        want, _ = forward(cfg, direct, toks, jnp.int32(0), KVCache.create(cfg, 1, jnp.float32), rope)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_moe_expert_parallel_matches_single_device(rng):
    """ep=2 x tp=2 sharded forward == single-device forward."""
    from dllama_tpu.engine.engine import InferenceEngine
    from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
    from dllama_tpu.parallel.sharding import LlamaShardings

    cfg = moe_cfg()
    params = random_params(cfg, seed=5, dtype=jnp.float32, quantize=False)
    toks = np.asarray(rng.integers(0, cfg.vocab_size, (1, 6)), np.int32)

    ref = InferenceEngine(cfg, params, cache_dtype=jnp.float32, attn_impl="jnp")
    ref_logits = np.asarray(ref.prefill(toks))

    mesh = make_mesh(MeshConfig(ep=2, tp=2), devices=jax.devices()[:4])
    sh = LlamaShardings(mesh, cfg)
    eng = InferenceEngine(cfg, params, cache_dtype=jnp.float32, shardings=sh, attn_impl="jnp")
    got_logits = np.asarray(eng.prefill(toks))
    np.testing.assert_allclose(got_logits, ref_logits, atol=1e-4, rtol=1e-4)


def test_hf_moe_tensor_stacking():
    from dllama_tpu.tools.converter_core import hf_tensor_for

    cfg = moe_cfg(experts=2)
    store = {}
    for e in range(2):
        store[f"model.layers.0.block_sparse_moe.experts.{e}.w1.weight"] = np.full(
            (cfg.hidden_dim, cfg.dim), float(e), np.float32
        )
    x = hf_tensor_for("layers.0.moe_w1", cfg, store.__getitem__)
    assert x.shape == (2, cfg.hidden_dim, cfg.dim)
    assert x[1].min() == 1.0 and x[0].max() == 0.0


def test_moe_dispatch_matches_dense_when_capacity_suffices(rng):
    """The O(k) dispatch path must agree with the dense reference whenever no
    token exceeds expert capacity (cf = E/k makes C = N: drop-free)."""
    cfg = moe_cfg(experts=8, active=2)
    h = jnp.asarray(rng.standard_normal((2, 8, cfg.dim)), jnp.float32)
    gate = jnp.asarray(rng.standard_normal((cfg.dim, 8)), jnp.float32)
    ws = [jnp.asarray(rng.standard_normal(s), jnp.float32) * 0.1
          for s in [(8, cfg.dim, cfg.hidden_dim), (8, cfg.hidden_dim, cfg.dim),
                    (8, cfg.dim, cfg.hidden_dim)]]
    got = moe_ffn(cfg, h, gate, *ws, impl="dispatch", capacity_factor=8 / 2)
    want = moe_ffn(cfg, h, gate, *ws, impl="dense")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4)


def test_moe_dispatch_capacity_drop_semantics(rng):
    """Tokens beyond an expert's capacity lose that expert's contribution
    (switch-transformer semantics): route everything to expert 0 with k=1 and
    a tight capacity — the first C tokens match dense, the rest are zero."""
    cfg = moe_cfg(experts=4, active=1)
    n = 8
    # positive activations so the all-ones gate column wins for every token
    h = jnp.asarray(np.abs(rng.standard_normal((1, n, cfg.dim))), jnp.float32)
    gate = jnp.zeros((cfg.dim, 4), jnp.float32).at[:, 0].set(1.0)  # all -> e0
    ws = [jnp.asarray(rng.standard_normal(s), jnp.float32) * 0.1
          for s in [(4, cfg.dim, cfg.hidden_dim), (4, cfg.hidden_dim, cfg.dim),
                    (4, cfg.dim, cfg.hidden_dim)]]
    got = np.asarray(moe_ffn(cfg, h, gate, *ws, impl="dispatch", capacity_factor=1.0))
    dense = np.asarray(moe_ffn(cfg, h, gate, *ws, impl="dense"))
    c = 2  # ceil(1 * 1 * 8 / 4)
    np.testing.assert_allclose(got[0, :c], dense[0, :c], atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(got[0, c:], 0.0, atol=1e-6)
