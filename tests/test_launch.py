"""Model-zoo launcher tests (tools/launch.py) — registry integrity and the
download path exercised offline via file:// URLs."""

import os

import pytest

from dllama_tpu.tools import launch


def test_registry_matches_reference_zoo():
    # the six models of the reference zoo (launch.py:15-46), incl. multipart
    names = set(launch.MODELS)
    assert {
        "llama3_2_1b_instruct_q40", "llama3_2_3b_instruct_q40",
        "llama3_1_8b_instruct_q40", "llama3_3_70b_instruct_q40",
        "llama3_1_405b_instruct_q40", "deepseek_r1_distill_llama_8b_q40",
    } == names
    assert len(launch.MODELS["llama3_1_405b_instruct_q40"].model_urls) == 56
    assert len(launch.MODELS["llama3_3_70b_instruct_q40"].model_urls) == 11
    assert launch._parts(3) == ["aa", "ab", "ac"]
    for m in launch.MODELS.values():
        assert all(u.startswith("https://") for u in m.model_urls)


def test_download_multipart_concatenates(tmp_path, capsys):
    parts = [tmp_path / f"part{i}" for i in range(3)]
    for i, p in enumerate(parts):
        p.write_bytes(bytes([i]) * 10)
    out = str(tmp_path / "joined.bin")
    launch.download_file([f"file://{p}" for p in parts], out)
    assert open(out, "rb").read() == b"\x00" * 10 + b"\x01" * 10 + b"\x02" * 10
    # second call skips (resume semantics)
    launch.download_file([f"file://{parts[0]}"], out)
    assert "skipping" in capsys.readouterr().out
    assert os.path.getsize(out) == 30


def test_download_failure_is_clean(tmp_path):
    out = str(tmp_path / "x.bin")
    with pytest.raises(SystemExit, match="download failed"):
        launch.download_file([f"file://{tmp_path}/missing"], out)
    assert not os.path.exists(out) and not os.path.exists(out + ".part")


def test_cli_list_and_run(capsys):
    assert launch.main(["list"]) == 0
    out = capsys.readouterr().out
    assert "llama3_1_8b_instruct_q40" in out and "238.0 GB" in out
    assert launch.main(["run", "llama3_2_1b_instruct_q40", "--dir", "m"]) == 0
    out = capsys.readouterr().out
    assert "-m dllama_tpu chat" in out and "m/dllama_model_llama3_2_1b_instruct_q40.m" in out
    assert "--max-seq-len 4096" in out


def test_examples_determinism_runs():
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.getcwd())
    r = subprocess.run(
        [sys.executable, "examples/determinism.py"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "deterministic" in r.stdout
