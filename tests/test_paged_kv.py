"""Paged KV cache tests (ISSUE 5): dense-vs-paged bit-exact parity across
sampling modes and overlap on/off, refcounted page lifecycle on release
rewinds, copy-on-write after prefix shares, capacity-aware admission
(deferral + eventual admit), and the shared-pages gauge.

The parity contract mirrors test_overlap.py's: with fixed prompts/seeds/
chunk, `--kv-layout dense` and `--kv-layout paged` (full-coverage pool)
produce BIT-IDENTICAL token streams — paging changes where KV rows live,
never what the device computes. Tiny config + memoized workloads keep this
file inside the time-budgeted tier-1 window."""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.batch import BatchEngine, PageExhausted
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.obs import instruments as ins
from dllama_tpu.serve.scheduler import Scheduler

CFG = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=96, seq_len=64)
PARAMS = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)
PAGE = 8  # 8 blocks per 64-row context


def _engine(layout, n_slots=3, spec=0, kv_pages=0):
    return BatchEngine(CFG, PARAMS, n_slots=n_slots, cache_dtype=jnp.float32,
                       spec=spec, kv_layout=layout, page_size=PAGE,
                       kv_pages=kv_pages)


def _make_sched(layout, overlap=True, n_slots=3, chunk=3, spec=0, kv_pages=0):
    return Scheduler(_engine(layout, n_slots, spec, kv_pages), chunk=chunk,
                     overlap=overlap)


_WORKLOADS: dict = {}


def _run_workload(layout, overlap=True, spec=0):
    """Mixed workload (greedy + sampled + penalized, staggered submission);
    memoized per (layout, overlap, spec) — every parity test compares the
    same runs, and each engine costs a compile inside the tier-1 budget."""
    key = (layout, overlap, spec)
    if key in _WORKLOADS:
        return _WORKLOADS[key]
    sched = _make_sched(layout, overlap=overlap, spec=spec)
    try:
        r1 = sched.submit([1, 2, 3, 1, 2, 3], 0.0, 0.9, 12, frozenset(), seed=1)
        it1 = r1.tokens()
        head = [next(it1), next(it1)]  # r1 decodes before the others join
        r2 = sched.submit([9, 8, 7], 1.1, 0.9, 10, frozenset(), seed=42)
        r3 = sched.submit([4, 5], 0.9, 0.8, 8, frozenset(), seed=7,
                          presence=0.5, frequency=0.3)
        out2 = list(r2.tokens())
        out3 = list(r3.tokens())
        out1 = head + list(it1)
        _WORKLOADS[key] = [(out1, r1.finish_reason), (out2, r2.finish_reason),
                           (out3, r3.finish_reason)]
        return _WORKLOADS[key]
    finally:
        sched.shutdown()


# -------------------------------------------------------------------- parity


def test_paged_parity_mixed_batch():
    """Greedy + sampled + penalized requests: paged streams are bit-identical
    to dense, and paged overlap-on matches paged overlap-off."""
    dense = _run_workload("dense")
    assert _run_workload("paged") == dense
    assert _run_workload("paged", overlap=False) == dense


def test_paged_parity_with_spec():
    """Batched speculative decoding over the paged pool: same streams as the
    dense spec engine AND as the non-spec runs (spec is bit-exact greedy)."""
    dense_spec = _run_workload("dense", spec=4)
    assert _run_workload("paged", spec=4) == dense_spec
    assert dense_spec == _run_workload("dense")


def test_flash_paged_matches_jnp_gather(rng):
    """Op-level: the block-table-indexed flash kernel (interpret mode)
    matches the jnp gather reference on a shuffled page pool."""
    from dllama_tpu.ops.layers import paged_gqa_attention
    from dllama_tpu.ops.pallas.flash_attention import (
        paged_flash_gqa_attention,
        paged_supported,
    )

    b, t, hq, hkv, hd, page, nb = 2, 1, 4, 2, 64, 64, 2
    assert paged_supported((hq, hd), page)
    p = b * nb
    q = jnp.asarray(rng.standard_normal((b, t, hq, hd)), jnp.float32)
    pool_k = jnp.asarray(rng.standard_normal((p + 1, hkv, page, hd)), jnp.float32)
    pool_v = jnp.asarray(rng.standard_normal((p + 1, hkv, page, hd)), jnp.float32)
    tables = jnp.asarray(rng.permutation(p).reshape(b, nb), jnp.int32)
    for pos in ([70, 17], [0, 127]):
        pos = jnp.asarray(pos, jnp.int32)
        want = paged_gqa_attention(q, pool_k, pool_v, tables, pos)
        got = paged_flash_gqa_attention(q, pool_k, pool_v, tables, pos,
                                        interpret=True)
        np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                                   atol=2e-5, rtol=2e-5)


# ------------------------------------------------------------ page lifecycle


def test_refcounted_free_on_release_rewind():
    """release(keep_rows=) returns exactly the tail pages; pages shared with
    another slot lose one reference without being freed."""
    eng = _engine("paged", n_slots=2)
    pool = eng.pool
    eng.add(0, list(range(1, 21)), temperature=0.0, seed=0)  # 20 rows
    eng.decode(8)  # pos 28 -> 4 pages
    assert pool.covered_rows(0) >= 28
    used_before = pool.stats()["used"]
    eng.release(0, keep_rows=10)  # keep 2 pages, free the rest
    st = pool.stats()
    assert st["used"] == used_before - (used_before - 2)
    assert pool.covered_rows(0) == 16 and int(eng.pos[0]) == 10

    # share the kept prefix into slot 1 (page-aligned: 8 rows = 1 full page)
    eng.copy_prefix_rows(0, 1, 8)
    shared_page = int(pool.tables[0, 0])
    assert int(pool.tables[1, 0]) == shared_page
    assert pool.refcount[shared_page] == 2 and pool.stats()["shared"] == 1
    # releasing the sharer decrements, never frees, the shared page
    free_before = pool.free_count
    eng.release(1, keep_rows=None)
    assert pool.refcount[shared_page] == 1
    assert pool.free_count == free_before  # slot 1 held no exclusive pages
    # releasing the owner finally frees it
    eng.release(0, keep_rows=None)
    assert pool.refcount[shared_page] == 0 and pool.stats()["used"] == 0


def test_cow_on_divergence_after_prefix_share():
    """An admission that diverges INSIDE a shared page copy-on-writes it:
    the donor's rows are untouched and its continuation is unchanged."""
    eng = _engine("paged", n_slots=2)
    solo = _engine("paged", n_slots=2)
    pool = eng.pool
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]  # exactly one page
    for e in (eng, solo):
        e.add(0, prompt, temperature=0.0, seed=0)
        e.release(0, keep_rows=8)
    eng.copy_prefix_rows(0, 1, 8)  # slot 1 aliases slot 0's page
    page0 = int(pool.tables[0, 0])
    assert pool.refcount[page0] == 2
    # admit into slot 1 with only 5 shared rows: rows 5.. of the SHARED page
    # are rewritten -> must copy-on-write before the scatter
    eng.add(1, [50, 51, 52], temperature=0.0, seed=2, start_pos=5)
    assert pool.refcount[page0] == 1, "divergence must un-share the page"
    assert int(pool.tables[1, 0]) != page0
    # the donor's cached rows survived: its continuation matches the engine
    # that never shared anything
    eng.release(1)
    eng.add(0, [9, 10], temperature=0.0, seed=1, start_pos=8)
    solo.add(0, [9, 10], temperature=0.0, seed=1, start_pos=8)
    np.testing.assert_array_equal(eng.decode(4)[:, 0], solo.decode(4)[:, 0])


def test_paged_capacity_exceeds_dense_footprint():
    """The acceptance-criterion capacity demo: 6 concurrent slots whose
    aggregate slot*seq_len demand (384 rows) exceeds the pool (128 rows =
    a 2-slot dense cache), all admitted and decoding AT ONCE — the dense
    layout cannot hold 6 concurrent sequences in that footprint."""
    from dllama_tpu.utils.profiling import cache_nbytes

    kv_pages = 16  # 16 * 8 = 128 rows
    eng = _engine("paged", n_slots=6, kv_pages=kv_pages)
    dense2 = _engine("dense", n_slots=2)
    # the pool's persistent footprint is at most the 2-slot dense cache (+1
    # trash page)
    assert cache_nbytes(eng.cache) <= cache_nbytes(dense2.cache) * (
        kv_pages + 1) / kv_pages
    assert 6 * CFG.seq_len > kv_pages * PAGE  # demand really overcommits
    for s in range(6):
        eng.add(s, [s + 1, s + 2, s + 3], temperature=0.0, seed=s)
    assert eng.active.all()  # all six admitted CONCURRENTLY
    toks = eng.decode(6)
    assert toks.shape == (6, 6)
    assert (eng.pos[:6] == 9).all()
    # and a prompt the pool can never hold fails loudly for direct callers
    eng.release(0)
    with pytest.raises((PageExhausted, ValueError)):
        eng.add(0, list(range(1, 60)), temperature=0.0, seed=9)


# -------------------------------------------------- capacity-aware admission


def test_admission_defers_until_pages_free():
    """Pool exhaustion defers admission (no slot assigned, no failure) and
    the request is admitted once a release frees pages — the scheduler's
    capacity = free pages, not free slots."""
    sched = _make_sched("paged", n_slots=3, chunk=3, kv_pages=8)  # 64 rows
    try:
        # r1: 40-row prompt -> 5 pages + decode reserve; its 20-token budget
        # grows it to 60 rows = ALL 8 pool pages while it runs
        r1 = sched.submit(list(range(1, 41)), 0.0, 0.9, 20, frozenset(), seed=1)
        it1 = r1.tokens()
        next(it1)
        # r2 needs ceil(30/8)+1 = 5 pages; at most 3 are ever free -> defer
        r2 = sched.submit(list(range(30, 60)), 0.0, 0.9, 4, frozenset(), seed=2)
        import time as _t

        deadline = _t.monotonic() + 30
        while not sched.health()["admission_deferred"]:
            assert _t.monotonic() < deadline, "admission never deferred"
            _t.sleep(0.01)
        assert r2.slot == -1  # parked, not admitted, not failed
        out1 = [next(it1) for _ in range(19)] + list(it1)
        out2 = list(r2.tokens())  # r1's release freed its pages
        assert r1.finish_reason == "length" and len(out1) + 1 == 20
        assert r2.finish_reason == "length" and len(out2) == 4
        assert not sched.health()["admission_deferred"]
    finally:
        sched.shutdown()


def test_oversized_prompt_rejected_not_deadlocked():
    """A prompt no empty pool could ever back fails fast with an error
    instead of deferring forever (and blocking the queue behind it)."""
    sched = _make_sched("paged", n_slots=2, chunk=3, kv_pages=8)
    try:
        # needs ceil(50/8)+1 = 8 pages... pool holds 8; make it need 9
        r = sched.submit(list(range(1, 60)), 0.0, 0.9, 4, frozenset(), seed=1)
        with pytest.raises(ValueError, match="KV pages"):
            list(r.tokens())
        assert r.finish_reason == "error"
        # the scheduler still serves well-sized requests afterwards
        ok = sched.submit([1, 2, 3], 0.0, 0.9, 4, frozenset(), seed=2)
        assert len(list(ok.tokens())) == 4
    finally:
        sched.shutdown()


def test_cross_slot_share_moves_shared_gauge():
    """Scheduler-level prefix reuse in paged mode shares pages instead of
    copying rows: the dllama_kv_pages_shared gauge goes positive when a
    request admits off an ACTIVE donor's cached prefix (the acceptance
    criterion's gauge check), and the reuse counter moves like dense."""
    sched = _make_sched("paged", n_slots=3, chunk=3)
    try:
        prompt_a = [1, 2, 3, 4, 5, 6, 7, 8]  # one full page
        ra = sched.submit(prompt_a, 0.0, 0.9, 4, frozenset(), seed=1)
        list(ra.tokens())  # slot cached with prompt_a + 4 tokens
        # rb takes the cached slot itself (longest idle prefix) and stays
        # ACTIVE while rc arrives; rc's only donor is then rb's busy slot ->
        # cross-slot page share into a fresh slot
        rb = sched.submit(prompt_a + [70], 0.0, 0.9, 30, frozenset(), seed=2)
        itb = rb.tokens()
        next(itb)
        before = sched.reused_prefix_tokens
        rc = sched.submit(prompt_a + [80], 0.0, 0.9, 4, frozenset(), seed=3)
        out_c = list(rc.tokens())
        assert len(out_c) == 4 and rc.finish_reason == "length"
        assert sched.reused_prefix_tokens - before >= len(prompt_a)
        assert ins.KV_PAGES_SHARED.value() >= 1, (
            "cross-slot prefix reuse must SHARE pages, not copy rows")
        assert sched.engine.pool.stats()["shared"] >= 1
        list(itb)
    finally:
        sched.shutdown()


def test_pool_audit_detects_corruption_and_double_free():
    """PagePool.audit(): clean on a live pool; detects a fabricated
    refcount/table mismatch (raising + counting); _decref refuses to drive
    a refcount negative (the double-release guard)."""
    from dllama_tpu.engine.batch import PoolAuditError
    from dllama_tpu.obs import metrics

    eng = _engine("paged", n_slots=2)
    pool = eng.pool
    eng.add(0, list(range(1, 20)), temperature=0.0, seed=0)
    eng.decode(4)
    assert pool.audit()["ok"]  # live pool, invariants hold
    fails0 = metrics.REGISTRY.sample("dllama_kv_audit_failures_total") or 0.0
    # fabricate corruption: bump a live page's refcount with no table ref
    page = int(pool.tables[0, 0])
    pool.refcount[page] += 1
    with pytest.raises(PoolAuditError, match="refcount"):
        pool.audit()
    report = pool.audit(raise_on_fail=False)
    assert not report["ok"] and report["problems"]
    pool.refcount[page] -= 1  # restore
    assert pool.audit()["ok"]
    # double-release guard: a second free of the same tail raises instead
    # of silently going negative
    pool.refcount[page] = 0  # as if already released (free list untouched)
    with pytest.raises(PoolAuditError, match="double release"):
        pool.free_tail(0, 0)
    fails = metrics.REGISTRY.sample("dllama_kv_audit_failures_total")
    assert fails >= fails0 + 3  # two failed audits + the double-free guard


def test_deferred_request_cut_cleanly_at_drain():
    """deferred x drain: a capacity-parked request gets a clean terminal
    finish at drain (no hang), its client sees the drain error, every page
    returns to the pool, and the audit is clean."""
    from dllama_tpu.serve.scheduler import SchedulerDraining
    from dllama_tpu.utils import faults

    sched = _make_sched("paged", n_slots=3, chunk=3, kv_pages=8)
    try:
        # slow chunks: r1 must still be running (and r2 still parked) when
        # the drain window closes
        faults.install("engine.decode", "delay", ms=30.0)
        r1 = sched.submit(list(range(1, 41)), 0.0, 0.9, 200, frozenset(),
                          seed=1)
        it1 = r1.tokens()
        next(it1)
        r2 = sched.submit(list(range(30, 60)), 0.0, 0.9, 4, frozenset(),
                          seed=2)
        import time as _t

        deadline = _t.monotonic() + 30
        while not sched.health()["admission_deferred"]:
            assert _t.monotonic() < deadline, "admission never deferred"
            _t.sleep(0.01)
        assert sched.drain(0.2) is False  # r1 outlives the window
        toks2 = []
        exc2 = None
        try:
            for t in r2.tokens():
                toks2.append(t)
        except SchedulerDraining as e:
            exc2 = e
        assert exc2 is not None and toks2 == []
        assert r2.finish_reason == "shutdown" and r2.slot == -1
        pool = sched.engine.pool
        assert pool.audit()["ok"]
        for s in range(sched.engine.n_slots):
            if not sched.engine.active[s]:
                sched.engine.drop_slot_pages(s)
        if sched.engine.radix is not None:
            # the radix tree's page refs are cache (committed prompts),
            # not leaks — drop them before the zero-leak assertion
            sched.engine.radix.clear()
        assert pool.stats()["used"] == 0, "drain leaked pages"
    finally:
        faults.clear()
        sched.shutdown()


def test_deferred_request_survives_restart():
    """deferred x restart: a worker crash with a capacity-parked head does
    not lose it — the running request resumes, the deferred one admits once
    pages free, and the rebuilt pool audits clean with zero leaks."""
    from dllama_tpu.utils import faults

    sched = _make_sched("paged", n_slots=3, chunk=3, kv_pages=8)
    sched.restart_max = 3
    sched.restart_backoff_s = 0.01
    try:
        warm = sched.submit([5, 6], 0.0, 0.9, 2, frozenset())
        list(warm.tokens())  # compile warm-up
        # slow every decode chunk a little: on a compile-warm CPU r1's whole
        # 8-token run takes ~3 fast chunks, so the window in which r2 sits
        # capacity-deferred is a few ms — narrower than the poll below, and
        # the test raced it (the pre-existing tier-1 flake this fixes). The
        # delay pins the deferred window open for ~hundreds of ms without
        # changing any scheduling semantics.
        faults.install("engine.decode", "delay", ms=30, times=40)
        # budget 8: prompt 40 + at most 7 resumed rows needs 7 pages incl.
        # the decode reserve, so the resume ALWAYS fits the 8-page pool no
        # matter how far r1 got before the crash
        r1 = sched.submit(list(range(1, 41)), 0.0, 0.9, 8, frozenset(),
                          seed=1)
        it1 = r1.tokens()
        next(it1)
        r2 = sched.submit(list(range(30, 60)), 0.0, 0.9, 4, frozenset(),
                          seed=2)
        import time as _t

        deadline = _t.monotonic() + 30
        while not sched.health()["admission_deferred"]:
            assert _t.monotonic() < deadline, "admission never deferred"
            _t.sleep(0.002)
        faults.install("scheduler.loop", "raise", times=1)
        out1 = list(it1)
        out2 = list(r2.tokens())
        assert r1.finish_reason == "length" and len(out1) + 1 == 8
        assert r2.finish_reason == "length" and len(out2) == 4
        h = sched.health()
        assert h["live"] and h["restarts"] == 1
        assert not h["admission_deferred"]
        pool = sched.engine.pool
        assert pool.audit()["ok"]
        for s in range(sched.engine.n_slots):
            if not sched.engine.active[s]:
                sched.engine.drop_slot_pages(s)
        if sched.engine.radix is not None:
            sched.engine.radix.clear()  # tree refs are cache, not leaks
        assert pool.stats()["used"] == 0, "restart recovery leaked pages"
    finally:
        faults.clear()
        sched.shutdown()


def test_all_slots_starved_finishes_one_to_free_pages():
    """Pool dry with every active slot starved: the scheduler finishes the
    most-advanced request ('length') so its pages un-freeze the rest —
    bounded truncation instead of livelock."""
    sched = _make_sched("paged", n_slots=2, chunk=4, kv_pages=6)  # 48 rows
    try:
        # two requests wanting 40+ rows each (80 > 48): they must both still
        # FINISH (one truncated early by the starvation break)
        r1 = sched.submit([1, 2, 3], 0.0, 0.9, 40, frozenset(), seed=1)
        r2 = sched.submit([4, 5, 6], 0.0, 0.9, 40, frozenset(), seed=2)
        out1, out2 = list(r1.tokens()), list(r2.tokens())
        assert r1.finish_reason == "length" and r2.finish_reason == "length"
        assert len(out1) >= 1 and len(out2) >= 1
        # at least one was cut before its token budget by pool exhaustion
        assert len(out1) < 40 or len(out2) < 40
        st = sched.engine.pool.stats()
        assert st["used"] == 0 or st["used"] <= 6
    finally:
        sched.shutdown()


# -------------------------------------------------- host-RAM spill tier
# (ISSUE 16): radix eviction swaps cold pages d2h instead of discarding;
# a returning prompt restores them h2d at admission, byte-identical


def _host_engine(kv_pages=12, host_pages=6, n_slots=3):
    return BatchEngine(CFG, PARAMS, n_slots=n_slots, cache_dtype=jnp.float32,
                       kv_layout="paged", page_size=PAGE, kv_pages=kv_pages,
                       radix_cache="on", kv_host_pages=host_pages)


def _tree_page_map(eng):
    """{absolute token path through each page: device page index} for every
    page the radix tree currently references."""
    out = {}

    def walk(node, prefix):
        for ch in node.children.values():
            full = prefix + tuple(ch.tokens)
            start = len(prefix)
            for i, p in enumerate(ch.pages):
                out[full[:start + (i + 1) * PAGE]] = p
            walk(ch, full)

    walk(eng.radix.root, ())
    return out


def _page_bytes(eng, page):
    kpg, vpg = eng._read_page(eng.cache, jnp.int32(page))
    return np.asarray(kpg), np.asarray(vpg)


def test_host_tier_spill_restore_byte_identity():
    """Evict -> spill d2h -> returning prompt restores h2d: the restored
    device pages are byte-identical to the pre-eviction ones, the lookup
    covers every full page again (only the partial boundary page needs
    re-prefill), counters/gauges reconcile, and the token stream repeats
    bit-exact."""
    from dllama_tpu.obs import metrics

    eng = _host_engine()
    sched = Scheduler(eng, chunk=4, overlap=False)
    try:
        prompt = list(range(1, 18))  # 17 tokens -> 2 full pages of 8
        r1 = sched.submit(list(prompt), 0.0, 0.9, 6, frozenset(), seed=1)
        out1 = list(r1.tokens())
        before = {path: _page_bytes(eng, p)
                  for path, p in _tree_page_map(eng).items()}
        assert before, "radix tree should hold the finished request's pages"
        host = eng.pool.host
        out0 = ins.KV_SPILL.labels(direction="out").value()
        in0 = ins.KV_SPILL.labels(direction="in").value()
        freed = eng.radix_evict(100)
        assert freed >= len(before)
        assert host.used == len(before)
        assert host.stats()["spilled"] == len(before)
        assert ins.KV_SPILL.labels(
            direction="out").value() - out0 == len(before)
        assert metrics.REGISTRY.sample(
            "dllama_kv_host_pages_used") == float(len(before))
        assert eng.pool.audit()["ok"]
        # the returning prompt restores every FULL page from the host tier
        rows, hit = eng.radix_lookup(list(prompt))
        assert rows == ((len(prompt) - 1) // PAGE) * PAGE == 16
        assert host.used == 0
        assert host.stats()["restored"] == len(before)
        assert ins.KV_SPILL.labels(direction="in").value() - in0 \
            == len(before)
        after = _tree_page_map(eng)
        assert set(after) == set(before)
        for path, p in after.items():
            k_new, v_new = _page_bytes(eng, p)
            np.testing.assert_array_equal(k_new, before[path][0])
            np.testing.assert_array_equal(v_new, before[path][1])
        assert eng.pool.audit()["ok"]
        # the same request repeats bit-exact THROUGH the restored pages
        r2 = sched.submit(list(prompt), 0.0, 0.9, 6, frozenset(), seed=1)
        assert list(r2.tokens()) == out1
        assert eng.pool.audit()["ok"]
    finally:
        sched.shutdown()


def test_host_tier_audit_catches_leaked_page():
    """A host entry the pool didn't publish (leak stand-in: unaligned key,
    wrong payload geometry, gauge drift) must fail PagePool.audit() loudly
    and count on dllama_kv_audit_failures_total."""
    from dllama_tpu.engine.batch import PoolAuditError
    from dllama_tpu.obs import metrics

    eng = _host_engine()
    host = eng.pool.host
    assert eng.pool.audit()["ok"]
    fails0 = metrics.REGISTRY.sample("dllama_kv_audit_failures_total") or 0.0
    bogus = np.zeros((CFG.n_layers, CFG.n_kv_heads, 3,
                      CFG.dim // CFG.n_heads), np.float32)
    host._entries[(1, 2, 3)] = (bogus, bogus)  # 3-token key, 3-row payload
    with pytest.raises(PoolAuditError):
        eng.pool.audit()
    report = eng.pool.audit(raise_on_fail=False)
    assert not report["ok"]
    assert any("host" in p for p in report["problems"])
    del host._entries[(1, 2, 3)]
    host._publish()
    assert eng.pool.audit()["ok"]
    assert metrics.REGISTRY.sample("dllama_kv_audit_failures_total") \
        >= fails0 + 2


def test_warm_restart_drops_both_tiers_together():
    """Warm restart must reset the HOST tier with the device tier: stale
    host payloads surviving a restart would be restored into a rebuilt
    pool whose contents they no longer match."""
    eng = _host_engine()
    sched = Scheduler(eng, chunk=4, overlap=False)
    prompt = list(range(1, 18))
    try:
        r1 = sched.submit(list(prompt), 0.0, 0.9, 4, frozenset(), seed=1)
        list(r1.tokens())
    finally:
        sched.shutdown()
    eng.radix_evict(100)
    host = eng.pool.host
    assert host.used > 0
    eng.warm_restart()
    host2 = eng.pool.host
    assert host2 is not host, "restart must rebuild the host pool"
    assert host2.used == 0 and host2.stats()["spilled"] == 0
    rows, _hit = eng.radix_lookup(list(prompt))
    assert rows == 0  # both tiers gone: nothing to restore from
    assert eng.pool.audit()["ok"]
