"""Compile & device-traffic observability (ISSUE 13, obs/compile).

Contracts driven here:

* shape-bucket classification: declared keys are expected, allow-range
  keys are expected, anything else on a DECLARED fn is unexpected (counter
  + structured warning), and fns with no declarations never false-alarm;
* the compile ledger is ground truth (jax.monitoring events, not a host
  shape model) and thread-safe under concurrent scoped dispatches;
* warmup report correctness: --warmup auto reaches full declared bucket
  coverage and the FIRST real request after it compiles NOTHING; a second
  warmup on the same engine finds everything cached;
* the acceptance drill: a steady-state decode window records ZERO compiles
  (unexpected or otherwise) and ZERO host->device upload bytes across
  {dense, paged} x overlap {on, off} x spec — under transfer_guard=strict,
  so an implicit upload raises instead of merely moving a counter;
* the strict guard really trips on an injected per-chunk upload.

Tiny 1-layer config + memoized engines, same discipline as test_hybrid.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.batch import BatchEngine
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.obs import compile as cobs
from dllama_tpu.obs import metrics

CFG = LlamaConfig(dim=32, hidden_dim=64, n_layers=1, n_heads=2, n_kv_heads=1,
                  vocab_size=64, seq_len=64)
PARAMS = random_params(CFG, seed=5, dtype=jnp.float32, quantize=False)
PAGE = 8


def _fresh_contract():
    """Install an empty contract (classification 'undeclared' everywhere)
    so unit tests are isolated from whatever engine ran last; returns the
    displaced contract for restoration."""
    old = cobs.LEDGER.contract
    cobs.LEDGER.install_contract(cobs.ShapeContract())
    return old


# ------------------------------------------------------------ contract unit


def test_contract_classification_expected_unexpected_undeclared():
    c = cobs.ShapeContract()
    c.declare("decode", "n1")
    c.declare("decode", "n4", warm=True)
    c.allow("decode", BatchEngine._n_in_range(1, 4))
    assert c.classify("decode", "n1") == "expected"
    assert c.classify("decode", "n4") == "expected"
    assert c.classify("decode", "n3") == "expected"  # allow-range clamp
    assert c.classify("decode", "n9") == "unexpected"
    assert c.classify("decode", "bogus") == "unexpected"
    # a fn with no declarations has no contract to violate
    assert c.classify("spec", "n1") == "undeclared"
    with pytest.raises(ValueError, match="unknown compile fn"):
        c.declare("not_a_fn", "x")


def test_contract_hybrid_keys_and_coverage():
    c = cobs.ShapeContract()
    for p in (1, 2, 4):
        c.declare("hybrid", f"p{p}.n3")
    c.allow("hybrid", BatchEngine._hybrid_in_range((1, 2, 4), 3))
    assert c.classify("hybrid", "p4.n3") == "expected"
    assert c.classify("hybrid", "p2.n1") == "expected"  # clamped decode len
    assert c.classify("hybrid", "p8.n3") == "unexpected"  # undeclared slice
    assert c.classify("hybrid", "p4.n7") == "unexpected"  # over-chunk
    cov = c.coverage({"hybrid": {"p1.n3", "p2.n3", "p2.n1", "p9.n9"}})
    h = cov["fns"]["hybrid"]
    assert h["declared"] == 3 and h["warm_targets"] == 3
    assert h["compiled"] == 2
    assert h["missing_warm"] == ["p4.n3"]
    assert h["unexpected_seen"] == ["p9.n9"]  # p2.n1 is allowed, not flagged
    assert cov["full"] is False
    cov2 = c.coverage({"hybrid": {"p1.n3", "p2.n3", "p4.n3"}})
    assert cov2["full"] is True


def test_sig_of():
    s = cobs.sig_of(jnp.zeros((2, 3), jnp.int32), 7, True)
    assert "int32[2,3]" in s and "7" in s and "True" in s


def test_transfer_accounting_snapshot():
    cobs.reset_transfers()
    base_b = metrics.REGISTRY.sample(
        "dllama_transfer_bytes_total",
        {"direction": "h2d", "site": "vectors"}) or 0.0
    cobs.note_transfer("h2d", "vectors", 100)
    cobs.note_transfer("h2d", "vectors", 20)
    cobs.note_transfer("d2h", "decode_tokens", 64)
    snap = cobs.transfer_snapshot()
    assert snap["sites"]["h2d.vectors"] == {"count": 2, "bytes": 120}
    assert snap["h2d"] == {"count": 2, "bytes": 120}
    assert snap["d2h"] == {"count": 1, "bytes": 64}
    # the registry counters moved in lockstep (lifetime, not reset)
    assert metrics.REGISTRY.sample(
        "dllama_transfer_bytes_total",
        {"direction": "h2d", "site": "vectors"}) == base_b + 120
    cobs.reset_transfers()
    assert cobs.transfer_snapshot()["h2d"]["bytes"] == 0


# ------------------------------------------------------------- ledger unit


def test_ledger_records_real_compiles_and_is_thread_safe():
    """Concurrent scoped dispatches over distinct shapes: every compile is
    attributed to its scope's (fn, key), totals are consistent, and cached
    re-calls record nothing."""
    old = _fresh_contract()
    cobs.LEDGER.reset()
    f = jax.jit(lambda x: x * 2 + 1)
    errs: list = []

    def worker(tid):
        try:
            for i in range(3):
                with cobs.LEDGER.scope("decode", f"t{tid}i{i}"):
                    f(jnp.zeros(8 + tid * 16 + i))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        snap = cobs.LEDGER.snapshot()
        assert snap["totals"]["decode"]["compiles"] == 12
        assert len(snap["seen"]["decode"]) == 12
        assert snap["totals"]["decode"]["unexpected"] == 0  # undeclared fn
        assert all(e["total_s"] > 0 for e in snap["entries"])
        # a cached re-dispatch records nothing
        before = cobs.LEDGER.total_compiles()
        with cobs.LEDGER.scope("decode", "t0i0"):
            f(jnp.zeros(8))
        assert cobs.LEDGER.total_compiles() == before
    finally:
        cobs.LEDGER.install_contract(old)


def test_unexpected_compile_classified_counted_and_logged(caplog):
    old = cobs.LEDGER.contract
    contract = cobs.ShapeContract()
    contract.declare("decode", "n1")
    contract.allow("decode", BatchEngine._n_in_range(1, 2))
    cobs.LEDGER.install_contract(contract)
    f = jax.jit(lambda x: x - 3.0)
    base = metrics.REGISTRY.sample(
        "dllama_jit_unexpected_compiles_total", {"fn": "decode"}) or 0.0
    try:
        import logging

        with caplog.at_level(logging.WARNING, logger="dllama_tpu.obs"):
            with cobs.LEDGER.scope("decode", "n9",
                                   sig=lambda: "f32[9]"):
                f(jnp.zeros(9))
        entry = cobs.LEDGER.snapshot()["entries"][-1]
        assert entry["classification"] == "unexpected"
        assert entry["key"] == "n9" and entry["sig"] == "f32[9]"
        assert metrics.REGISTRY.sample(
            "dllama_jit_unexpected_compiles_total",
            {"fn": "decode"}) == base + 1
        assert any("unexpected jit compile" in r.message
                   for r in caplog.records), "no structured warning"
        # an allowed clamp key stays expected
        with cobs.LEDGER.scope("decode", "n2"):
            f(jnp.zeros(2))
        assert (cobs.LEDGER.snapshot()["entries"][-1]["classification"]
                == "expected")
    finally:
        cobs.LEDGER.install_contract(old)


# ------------------------------------------------------ engines & warmup


_ENGINES: dict = {}


def _engine(layout, spec=0):
    key = (layout, spec)
    if key not in _ENGINES:
        _ENGINES[key] = BatchEngine(
            CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, spec=spec,
            kv_layout=layout, page_size=PAGE, max_prefill_chunk=4)
    return _ENGINES[key]


def test_warmup_report_full_coverage_then_zero_compile_request():
    """--warmup auto: the report covers every declared warm bucket, the
    first REAL request compiles nothing, and a second warmup on the same
    engine finds the whole universe cached."""
    from dllama_tpu.serve.scheduler import Scheduler

    cobs.LEDGER.reset()  # the ledger is process-global and earlier tests
    # deliberately recorded an unexpected compile — health() reports
    # lifetime totals, so this test wants a clean slate
    eng = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32,
                      kv_layout="paged", page_size=PAGE, max_prefill_chunk=4)
    sched = Scheduler(eng, chunk=2, warmup="auto")
    try:
        rep = sched.warmup_report
        assert rep is not None and rep["full_coverage"] is True
        assert rep["buckets"] == rep["compiled"] + rep["cached"]
        assert rep["compiled"] > 0 and rep["seconds"] > 0
        # decode + pen + prefill pow2s + commit + hybrid slices all warmed
        assert {"prefill_chunk", "commit", "decode", "decode_pen",
                "hybrid", "hybrid_pen"} <= set(rep["per_fn"])
        before = cobs.LEDGER.total_compiles()
        r = sched.submit([1, 2, 3, 4, 5], 0.0, 0.9, 5, frozenset(), seed=1)
        assert len(list(r.tokens())) == 5
        assert cobs.LEDGER.total_compiles() == before, (
            "a warmed engine's first request must pay zero compile")
        # the serving surfaces carry the record
        assert sched.latency_summary()["compile"]["warmup_mode"] == "auto"
        h = sched.health()["compile"]
        assert h["full_coverage"] is True and h["unexpected_compiles"] == 0
    finally:
        sched.shutdown()
    # second scheduler over the same engine: everything is already cached
    sched2 = Scheduler(eng, chunk=2, warmup="auto")
    try:
        rep2 = sched2.warmup_report
        assert rep2["compiled"] == 0 and rep2["cached"] == rep2["buckets"]
    finally:
        sched2.shutdown()


def test_warmup_rejects_busy_engine():
    eng = _engine("dense")
    if not eng.active.any():
        eng.add(0, [1, 2], temperature=0.0, seed=3)
    with pytest.raises(RuntimeError, match="before any slot is active"):
        eng.warmup(chunk=2)
    eng.release(0, None)


# --------------------------------------------------- steady-state drill


def _steady_window(eng, spec: bool, overlap: bool, chunks: int = 3) -> None:
    """Measure `chunks` steady-state decode (or spec) chunks under the
    strict transfer guard: total compiles and h2d upload bytes must both
    be exactly zero."""
    n = 2
    c0 = cobs.LEDGER.total_compiles()
    cobs.reset_transfers()
    if overlap:
        pending = eng.decode_dispatch(n, spec=spec)
        for _ in range(chunks - 1):
            nxt = eng.decode_dispatch(n, spec=spec)
            eng.decode_consume(pending)
            pending = nxt
        eng.decode_consume(pending)
    else:
        for _ in range(chunks):
            eng.decode_consume(eng.decode_dispatch(n, spec=spec))
    snap = cobs.transfer_snapshot()
    assert cobs.LEDGER.total_compiles() - c0 == 0, (
        f"steady-state window recompiled: "
        f"{cobs.LEDGER.snapshot()['entries'][-3:]}")
    assert snap["h2d"] == {"count": 0, "bytes": 0}, (
        f"steady-state host->device upload: {snap['sites']}")
    assert snap["d2h"]["bytes"] > 0  # tokens still materialize, of course


@pytest.mark.parametrize("layout,spec", [("dense", 0), ("dense", 2),
                                         ("paged", 0), ("paged", 2)])
def test_steady_state_zero_compiles_zero_uploads(layout, spec):
    """The acceptance drill: a 3-chunk steady-state decode records ZERO
    compiles and ZERO uploads — {dense, paged} x overlap {on, off} x spec,
    with transfer_guard=strict so an implicit upload raises."""
    eng = _engine(layout, spec)
    u0 = cobs.LEDGER.total_unexpected()
    if not eng.active.any():
        eng.add(0, [1, 2, 3], temperature=0.0, seed=1)
        eng.add(1, [4, 5, 6], temperature=0.0, seed=2)
    use_spec = spec > 0
    # warm past the admission boundary, then pre-provision the window's
    # pages (page allocation is an amortized boundary event, not per-chunk
    # traffic) and consume the resulting vector refresh with one chunk
    eng.decode_consume(eng.decode_dispatch(2, spec=use_spec))
    eng._alloc_decode_rows(48)
    eng.decode_consume(eng.decode_dispatch(2, spec=use_spec))
    eng.transfer_guard = "strict"
    try:
        _steady_window(eng, use_spec, overlap=False)
        _steady_window(eng, use_spec, overlap=True)
    finally:
        eng.transfer_guard = "off"
    assert cobs.LEDGER.total_unexpected() == u0, "contract flagged steady work"


def test_transfer_guard_strict_trips_on_injected_upload():
    """An injected host-resident decode carry (the exact per-chunk upload
    PR 3 eliminated) fails the dispatch loudly under strict mode. The
    engine's donated buffers are indeterminate after the failed launch, so
    the memoized engine is discarded."""
    eng = _ENGINES.pop(("dense", 0), None) or BatchEngine(
        CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32,
        kv_layout="dense", max_prefill_chunk=4)
    if not eng.active.any():
        eng.add(0, [1, 2, 3], temperature=0.0, seed=1)
    eng.decode(2)
    eng.transfer_guard = "strict"
    eng._last_dev = np.asarray(eng._last_dev)  # the injected upload
    with pytest.raises(Exception, match="(?i)transfer|disallow"):
        eng.decode_dispatch(2)
