"""Continuous-batching engine tests: slot isolation, staggered joins, parity
with the single-sequence engine, per-slot sampling params, vector-pos model
paths (the capability the reference's blocking server lacks, SURVEY §7.4.6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.engine.batch import BatchEngine
from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.engine.sampling import Sampler
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import KVCache, forward, random_params
from dllama_tpu.ops.layers import build_rope_cache


CFG = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=96, seq_len=64)
PARAMS = random_params(CFG, seed=9, dtype=jnp.float32, quantize=False)


def greedy_ref(prompt, n):
    eng = InferenceEngine(CFG, PARAMS, cache_dtype=jnp.float32)
    return list(eng.generate(prompt, n, Sampler(0.0, 0.9, 0)))


def test_vector_pos_forward_matches_scalar():
    """forward with pos=[p, p] must equal forward with scalar p."""
    rope = build_rope_cache(CFG)
    toks = jnp.asarray([[5, 6, 7], [8, 9, 10]], jnp.int32)
    c1 = KVCache.create(CFG, 2, jnp.float32)
    l1, c1 = forward(CFG, PARAMS, toks, jnp.int32(4), c1, rope)
    c2 = KVCache.create(CFG, 2, jnp.float32)
    l2, c2 = forward(CFG, PARAMS, toks, jnp.asarray([4, 4], jnp.int32), c2, rope)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(c1.k), np.asarray(c2.k), atol=1e-6, rtol=1e-6)


def test_active_mask_freezes_cache():
    rope = build_rope_cache(CFG)
    toks = jnp.asarray([[5], [8]], jnp.int32)
    c0 = KVCache.create(CFG, 2, jnp.float32)
    _, c1 = forward(CFG, PARAMS, toks, jnp.asarray([0, 0], jnp.int32), c0,
                    rope, active=jnp.asarray([True, False]))
    k = np.asarray(c1.k)
    assert np.abs(k[:, 0]).max() > 0  # row 0 written
    assert np.abs(k[:, 1]).max() == 0  # row 1 frozen


def test_batch_matches_single_engine_greedy():
    """Two sequences decoded together == each decoded alone."""
    p1, p2 = [1, 2, 3], [9, 8, 7, 6]
    want1, want2 = greedy_ref(p1, 8), greedy_ref(p2, 8)

    be = BatchEngine(CFG, PARAMS, n_slots=3, cache_dtype=jnp.float32)
    f1 = be.add(0, p1, temperature=0.0)
    f2 = be.add(2, p2, temperature=0.0)  # non-adjacent slot on purpose
    assert [f1, f2] == [want1[0], want2[0]]
    toks = be.decode(7)
    assert list(toks[:, 0]) == want1[1:]
    assert list(toks[:, 2]) == want2[1:]


def test_staggered_join_does_not_disturb_running_slot():
    """Join slot 1 after slot 0 already decoded 4 tokens; slot 0's continuation
    must be unchanged (prefill writes are masked to the joining slot)."""
    p1, p2 = [1, 2, 3], [20, 21]
    want1 = greedy_ref(p1, 10)
    want2 = greedy_ref(p2, 5)

    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    got1 = [be.add(0, p1, temperature=0.0)]
    got1 += list(be.decode(4)[:, 0])
    got2 = [be.add(1, p2, temperature=0.0)]
    toks = be.decode(4)
    got1 += list(toks[:, 0])
    got2 += list(toks[:, 1])
    assert got1 == want1[:9]
    assert got2 == want2[:5]


def test_release_and_reuse_slot():
    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    be.add(0, [1, 2, 3], temperature=0.0)
    be.decode(3)
    be.release(0)
    assert be.free_slot() == 0
    # fresh request in the recycled slot equals a fresh engine
    want = greedy_ref([4, 5], 5)
    got = [be.add(0, [4, 5], temperature=0.0)]
    got += list(be.decode(4)[:, 0])
    assert got == want[:5]


def test_per_slot_temperature_zero_is_greedy():
    """Greedy slot must be exact even when batched with a sampling slot."""
    p1 = [1, 2, 3]
    want = greedy_ref(p1, 6)
    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, seed=5)
    got = [be.add(0, p1, temperature=0.0)]
    be.add(1, [7, 8], temperature=1.2, topp=0.8)
    got += list(be.decode(5)[:, 0])
    assert got == want[:6]


def test_frozen_slot_repeats_last_token():
    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    be.add(0, [1, 2], temperature=0.0)
    be.decode(2)
    be.release(0)
    be.add(1, [3, 4], temperature=0.0)
    last0 = be.last_token[0]
    pos0_before = int(be.pos[0])
    toks = be.decode(3)
    assert (toks[:, 0] == last0).all()  # frozen slot unchanged
    assert be.pos[0] == pos0_before  # frozen pos not advanced by decode


def test_flash_attention_vector_pos(rng):
    from dllama_tpu.ops.layers import gqa_attention
    from dllama_tpu.ops.pallas.flash_attention import flash_gqa_attention

    q = jnp.asarray(rng.standard_normal((2, 1, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 2, 128, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 2, 128, 64)), jnp.float32)
    pos = jnp.asarray([3, 77], jnp.int32)
    got = flash_gqa_attention(q, k, v, pos, interpret=True)
    want = gqa_attention(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_batch_engine_sharded_matches_unsharded():
    """BatchEngine on a tp=2 x dp-style mesh == unsharded (multi-chip serving)."""
    from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
    from dllama_tpu.parallel.sharding import LlamaShardings

    be_ref = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    mesh = make_mesh(MeshConfig(tp=2), devices=jax.devices()[:2])
    sh = LlamaShardings(mesh, CFG)
    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, shardings=sh)

    p1, p2 = [1, 2, 3], [9, 8]
    a = [be_ref.add(0, p1, temperature=0.0), be_ref.add(1, p2, temperature=0.0)]
    b = [be.add(0, p1, temperature=0.0), be.add(1, p2, temperature=0.0)]
    assert a == b
    ta, tb = be_ref.decode(6), be.decode(6)
    np.testing.assert_array_equal(ta, tb)


def test_per_request_seed_reproducible_across_batch_composition():
    """VERDICT r1 weak #5: a seeded request samples the same continuation
    whether it runs alone or shares the batch (per-slot PRNG keys)."""
    p = [1, 2, 3]
    be1 = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    alone = [be1.add(0, p, temperature=1.1, topp=0.95, seed=123)]
    alone += list(be1.decode(6)[:, 0])

    be2 = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, seed=9)
    got = [be2.add(0, p, temperature=1.1, topp=0.95, seed=123)]
    be2.add(1, [7, 8, 9], temperature=0.7, topp=0.8, seed=77)  # batch-mate
    got += list(be2.decode(6)[:, 0])
    assert got == alone

    # and chunk boundaries don't change the stream
    be3 = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    got3 = [be3.add(0, p, temperature=1.1, topp=0.95, seed=123)]
    got3 += list(be3.decode(2)[:, 0])
    got3 += list(be3.decode(4)[:, 0])
    assert got3 == alone


def test_batch_engine_rejects_sp_mesh():
    from dllama_tpu.parallel.mesh import MeshConfig, make_mesh
    from dllama_tpu.parallel.sharding import LlamaShardings

    mesh = make_mesh(MeshConfig(sp=2, tp=2))
    sh = LlamaShardings(mesh, CFG)
    with pytest.raises(ValueError, match="tp/dp"):
        BatchEngine(CFG, PARAMS, n_slots=2, shardings=sh)


def test_slot_prefill_matches_masked_full_width():
    """The B=1 slot-sliced admission prefill must produce the same cache rows
    and first-token logits as the masked full-width step it replaces."""
    be_slot = BatchEngine(CFG, PARAMS, n_slots=3, seed=5, cache_dtype=jnp.float32)
    be_full = BatchEngine(CFG, PARAMS, n_slots=3, seed=5, cache_dtype=jnp.float32)
    assert be_slot._use_slot_prefill
    be_full._use_slot_prefill = False

    prompt = [5, 6, 7, 8, 9]
    t1 = be_slot.add(1, prompt, temperature=0.0, seed=11)
    t2 = be_full.add(1, prompt, temperature=0.0, seed=11)
    assert t1 == t2
    np.testing.assert_allclose(
        np.asarray(be_slot.cache.k, np.float32),
        np.asarray(be_full.cache.k, np.float32), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(be_slot.cache.v, np.float32),
        np.asarray(be_full.cache.v, np.float32), atol=1e-5, rtol=1e-5)
    # untouched slots remain zero
    assert float(np.abs(np.asarray(be_slot.cache.k, np.float32)[:, 0]).max()) == 0.0
    # and decode after slot-admission continues identically
    d1 = be_slot.decode(4)
    d2 = be_full.decode(4)
    np.testing.assert_array_equal(d1[:, 1], d2[:, 1])


def test_batch_engine_fused_weights_parity():
    """BatchEngine(fuse_weights=True) must match unfused decode exactly."""
    outs = {}
    for fused in (False, True):
        be = BatchEngine(CFG, PARAMS, n_slots=2, seed=7, cache_dtype=jnp.float32,
                         fuse_weights=fused)
        first = be.add(0, [3, 4, 5], temperature=0.0, seed=1)
        toks = be.decode(6)
        outs[fused] = (first, [int(t) for t in toks[:, 0]])
    assert outs[False] == outs[True]


def test_slot_prefill_start_pos_matches_full_width():
    """Prefix-cache admissions (start_pos > 0) must agree across the
    slot-sliced and masked full-width prefill paths (same cache, same first
    token): this is the path the scheduler's NaiveCache reuse drives."""
    be_slot = BatchEngine(CFG, PARAMS, n_slots=2, seed=9, cache_dtype=jnp.float32)
    be_full = BatchEngine(CFG, PARAMS, n_slots=2, seed=9, cache_dtype=jnp.float32)
    be_full._use_slot_prefill = False

    turn1 = [3, 4, 5, 6]
    for be in (be_slot, be_full):
        be.add(0, turn1, temperature=0.0, seed=2)
        be.release(0, keep_rows=len(turn1))  # keep KV rows (prefix cache)
    delta = [7, 8]
    t1 = be_slot.add(0, delta, temperature=0.0, seed=3, start_pos=len(turn1))
    t2 = be_full.add(0, delta, temperature=0.0, seed=3, start_pos=len(turn1))
    assert t1 == t2
    np.testing.assert_allclose(
        np.asarray(be_slot.cache.k, np.float32),
        np.asarray(be_full.cache.k, np.float32), atol=1e-5, rtol=1e-5)


# ------------------------------------------------- batched speculative decode


def _drain_spec(be, slots, n_want):
    """Run spec cycles until every tracked slot has n_want tokens; returns
    ({slot: tokens}, cycles)."""
    streams = {s: [] for s in slots}
    cycles = 0
    while any(len(v) < n_want for v in streams.values()):
        emit, adv = be.spec_step()
        cycles += 1
        for s in slots:
            streams[s] += list(emit[s, : adv[s]])
        assert cycles < 20 * n_want, "spec cycles not converging"
    return {s: v[:n_want] for s, v in streams.items()}, cycles


def test_spec_batched_greedy_exact():
    """Greedy slots under batched speculation emit the bit-identical stream
    of the single-sequence greedy reference, in fewer forwards once the
    continuations settle into their own loops (the draftable pattern —
    same mechanism as test_spec_accepts_drafts_on_repetitive_text)."""
    p1 = [1, 2, 3, 1, 2, 3, 1, 2]
    p2 = [9, 8, 7, 9, 8, 7, 9]
    n = 40  # long enough for tiny-model greedy to enter a short cycle
    want1, want2 = greedy_ref(p1, n + 1), greedy_ref(p2, n + 1)

    be = BatchEngine(CFG, PARAMS, n_slots=3, cache_dtype=jnp.float32, spec=4)
    f1 = be.add(0, p1, temperature=0.0)
    f2 = be.add(2, p2, temperature=0.0)
    assert [f1, f2] == [want1[0], want2[0]]
    streams, cycles = _drain_spec(be, (0, 2), n)
    assert streams[0] == want1[1 : n + 1]
    assert streams[2] == want2[1 : n + 1]
    # the whole point: fewer verify forwards than tokens
    assert cycles < n, f"no speculation win: {cycles} cycles for {n} tokens"


def test_spec_batched_sampled_slot_is_exact_and_reproducible():
    """A sampled slot advances exactly 1 token per cycle and its stream is
    reproducible from its seed, independent of greedy batch-mates."""

    def run():
        be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, spec=4)
        be.add(0, [1, 2, 3, 1, 2, 3], temperature=0.0)
        first = be.add(1, [5, 6, 7], temperature=0.9, seed=123)
        out = [first]
        for _ in range(6):
            emit, adv = be.spec_step()
            assert adv[1] == 1  # sampled slots never accept drafts
            out += list(emit[1, : adv[1]])
        return out

    a, b = run(), run()
    assert a == b and len(a) == 7


def test_spec_interleaves_with_decode_and_admissions():
    """decode() backfills the spec history, so alternating decode chunks,
    spec cycles, and a mid-stream admission still yields the exact greedy
    reference for every slot."""
    p1, p2 = [1, 2, 3, 1, 2, 3], [4, 5, 6, 4, 5]
    want1, want2 = greedy_ref(p1, 14), greedy_ref(p2, 9)

    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, spec=3)
    got1 = [be.add(0, p1, temperature=0.0)]
    got1 += list(be.decode(4)[:, 0])  # plain decode first
    got2 = [be.add(1, p2, temperature=0.0)]  # staggered admission
    streams, _ = _drain_spec(be, (0, 1), 8)
    got1 += streams[0]
    got2 += streams[1]
    assert got1 == want1[:13]
    assert got2 == want2[:9]


def test_spec_step_guards():
    be = BatchEngine(CFG, PARAMS, n_slots=1, cache_dtype=jnp.float32)
    with pytest.raises(ValueError, match="spec=0"):
        be.spec_step()
    be2 = BatchEngine(CFG, PARAMS, n_slots=1, cache_dtype=jnp.float32, spec=4)
    with pytest.raises(ValueError, match="no active"):
        be2.spec_step()
    # slot too close to seq_len for a K+1 window: frozen for spec, decode
    # still finishes it
    be2.add(0, list(range(1, 61)), temperature=0.0)  # pos 60 of 64, k+1=5
    with pytest.raises(ValueError, match="room"):
        be2.spec_step()
    be2.decode(2)


def test_spec_frozen_sampled_slot_keeps_seed_stream():
    """A sampled slot frozen out of spec cycles (near seq_len) must not
    consume PRNG splits while frozen: its continuation via decode() equals
    the same-seed run that never saw those cycles (the seed-pinned
    reproducibility contract, VERDICT r1 weak #5)."""

    def tail(with_spec_cycles):
        be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, spec=4)
        be.add(0, [1, 2, 3, 1, 2, 3], temperature=0.0)  # greedy batch-mate
        # sampled slot parked within k+1 of seq_len: room_ok False -> frozen
        be.add(1, list(range(1, 61)), temperature=0.9, seed=7)  # pos 60 of 64
        if with_spec_cycles:
            for _ in range(3):
                emit, adv = be.spec_step()
                assert adv[1] == 0  # frozen: emitted nothing
        return [int(t) for t in be.decode(3)[:, 1]]

    assert tail(False) == tail(True)


def test_spec_penalized_slot_rides_the_cycle():
    """A penalized slot no longer freezes spec cycles (ISSUE 11): the
    counts-carrying _spec_step_pen variant advances it exactly 1
    bit-exact penalized token per cycle while greedy batch-mates keep
    multi-token acceptance — no decode alternation needed (replaces the
    old engine-global freeze of VERDICT r4 next #6)."""
    from dllama_tpu.engine.sampling import Sampler as _S

    p_g, p_p = [1, 2, 3, 1, 2, 3, 1, 2], [7, 8, 9]
    n = 12
    want_g = greedy_ref(p_g, n + 1)
    eng1 = InferenceEngine(CFG, PARAMS, cache_dtype=jnp.float32)
    want_p = list(eng1.generate(p_p, n + 1, _S(temperature=0.0, presence=0.6,
                                               frequency=0.4)))

    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32, spec=4)
    got_g = [be.add(0, p_g, temperature=0.0)]
    got_p = [be.add(1, p_p, temperature=0.0, presence=0.6, frequency=0.4)]
    cycles = 0
    while len(got_g) < n + 1 or len(got_p) < n + 1:
        emit, adv = be.spec_step()
        cycles += 1
        assert adv[1] == 1  # penalized: exactly one penalized token
        got_g += [int(t) for t in emit[0, : adv[0]]]
        got_p += [int(emit[1, 0])]
        assert cycles < 20 * n, "not converging"
    assert got_g[: n + 1] == want_g[: n + 1]
    assert got_p[: n + 1] == want_p[: n + 1]


def test_batched_penalties_match_single_engine():
    """A penalized request in the batched tier must produce the same greedy
    stream as the single-engine penalized generate (same OpenAI
    sampled-token-counts semantics), while an un-penalized batch-mate's
    stream stays untouched."""
    from dllama_tpu.engine.sampling import Sampler as _S

    p1, p2 = [1, 2, 3], [7, 8, 9]
    eng1 = InferenceEngine(CFG, PARAMS, cache_dtype=jnp.float32)
    want_pen = list(eng1.generate(p1, 9, _S(temperature=0.0, presence=0.6,
                                            frequency=0.4)))
    want_plain = greedy_ref(p2, 9)

    be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
    got_pen = [be.add(0, p1, temperature=0.0, presence=0.6, frequency=0.4)]
    got_plain = [be.add(1, p2, temperature=0.0)]
    toks = be.decode(8)
    got_pen += [int(t) for t in toks[:, 0]]
    got_plain += [int(t) for t in toks[:, 1]]
    assert got_pen == want_pen
    assert got_plain == want_plain[:9]
    # recycled slot must not inherit penalties
    be.release(0)
    assert be.presence[0] == 0.0 and be.frequency[0] == 0.0


def test_batched_penalized_sampled_reproducible():
    """Penalized SAMPLED requests stay seed-reproducible and differ from the
    same seed without penalties (the penalty reshapes the distribution)."""

    def run(freq):
        be = BatchEngine(CFG, PARAMS, n_slots=2, cache_dtype=jnp.float32)
        out = [be.add(0, [1, 2, 3], temperature=1.0, topp=0.9, seed=42,
                      frequency=freq)]
        out += [int(t) for t in be.decode(8)[:, 0]]
        return out

    a, b = run(0.9), run(0.9)
    assert a == b  # reproducible under penalties
    assert run(0.0) != a  # and the penalty actually reshapes sampling
