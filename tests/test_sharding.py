"""Distributed correctness on the 8-device virtual CPU mesh.

Where the reference can only test multi-node by hand-spawning localhost
workers (examples/n-workers.sh, no CI coverage), these tests run the sharded
graph in-process and assert numerical equality with the single-device result.
"""

import numpy as np
import pytest

import jax

from dllama_tpu.parallel import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dllama_tpu.engine.engine import InferenceEngine
from dllama_tpu.engine.sampling import Sampler
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.models.llama import random_params
from dllama_tpu.parallel import collectives
from dllama_tpu.parallel.mesh import MeshConfig, auto_mesh_config, make_mesh
from dllama_tpu.parallel.sharding import LlamaShardings

# col-sharded Q40 weights shard the 32-element block axis: in_dim % (32*tp) == 0,
# hence dim 128 for tp<=4
CFG = LlamaConfig(
    dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4, vocab_size=128, seq_len=64
)


def test_mesh_axes_and_sizes():
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    assert mesh.axis_names == ("dp", "pp", "sp", "tp", "ep")
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4


@pytest.mark.parametrize("n,kv,expect_tp", [(8, 4, 4), (8, 6, 2), (8, 8, 8), (4, 1, 1), (8, 3, 1)])
def test_auto_mesh_config_valid(n, kv, expect_tp):
    mc = auto_mesh_config(n, kv)
    assert mc.n_devices == n
    assert kv % mc.tp == 0
    assert mc.tp == expect_tp


@pytest.mark.parametrize("mesh_cfg", [MeshConfig(tp=4), MeshConfig(dp=2, tp=4), MeshConfig(dp=2, tp=2)])
def test_tp_forward_matches_single_device(mesh_cfg):
    """The headline reproduction test: TP(+DP)-sharded decode == 1-device
    decode (the reference validates this only by running real clusters)."""
    params = random_params(CFG, seed=3, dtype=jnp.float32, quantize=True)
    prompt = np.array([[5, 9, 2, 7, 1, 3]], dtype=np.int32)

    ref = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    ref_logits = np.asarray(ref.prefill(prompt))

    mesh = make_mesh(mesh_cfg)
    sh = LlamaShardings(mesh, CFG)
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32, shardings=sh)
    got = np.asarray(eng.prefill(prompt))
    np.testing.assert_allclose(got, ref_logits, atol=2e-4, rtol=1e-3)

    # and one decode step through the sharded KV cache
    ref_l2 = np.asarray(ref.decode_step(np.array([[11]])))
    got_l2 = np.asarray(eng.decode_step(np.array([[11]])))
    np.testing.assert_allclose(got_l2, ref_l2, atol=2e-4, rtol=1e-3)


def test_sp_sharded_cache_matches():
    """Sequence-parallel KV cache (the axis the reference lacks, SURVEY §5.7)."""
    params = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)
    prompt = np.array([[5, 9, 2, 7]], dtype=np.int32)
    ref = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    ref_logits = np.asarray(ref.prefill(prompt))

    mesh = make_mesh(MeshConfig(sp=2, tp=2, dp=2))
    sh = LlamaShardings(mesh, CFG)
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32, shardings=sh)
    got = np.asarray(eng.prefill(prompt))
    np.testing.assert_allclose(got, ref_logits, atol=2e-4, rtol=1e-3)


def test_q80_all_gather_and_reduce():
    mesh = make_mesh(MeshConfig(tp=8))
    x = np.random.default_rng(0).normal(size=(8, 64)).astype(np.float32)

    @jax.jit
    def gather(x):
        return _shard_map(
            lambda s: collectives.q80_all_gather(s, "tp"),
            mesh=mesh,
            in_specs=P("tp", None),
            out_specs=P("tp", None),
        )(x)

    got = np.asarray(gather(jnp.asarray(x)))
    # each device sees all 8 rows, quantization-noise close
    assert got.shape == (64, 64)
    np.testing.assert_allclose(got[:8], x, atol=0.05)

    @jax.jit
    def reduce(x):
        return _shard_map(
            lambda s: collectives.q80_all_reduce(s, "tp"),
            mesh=mesh,
            in_specs=P("tp", None),
            out_specs=P(None, None),
            check_vma=False,  # value is replicated post all-gather+sum, but the
            # static checker can't prove it without a psum
        )(x)

    got = np.asarray(reduce(jnp.asarray(x)))
    np.testing.assert_allclose(got, x.sum(0, keepdims=True), atol=0.3)


def test_sharded_generate_runs():
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    sh = LlamaShardings(mesh, CFG)
    params = random_params(CFG, seed=0, dtype=jnp.bfloat16, quantize=True)
    eng = InferenceEngine(CFG, params, shardings=sh)
    toks = list(eng.generate([1, 2, 3], 5, Sampler(temperature=0.0)))
    assert len(toks) == 5


def test_shard_direct_load_never_stages_on_one_device(tmp_path):
    """VERDICT r1 weak #2: load_model must ship each tensor memmap->shards.
    The put callback must receive host (numpy-backed) leaves — proof that no
    full tensor was staged on a device first — and the loaded engine's params
    must carry the tp shardings and match single-device logits."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.models import formats
    from dllama_tpu.models.formats import load_params, read_header
    from dllama_tpu.ops.quant import FloatType, QTensor

    cfg = LlamaConfig(
        dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=128, seq_len=64, weight_type=FloatType.Q40,
    )
    rng = np.random.default_rng(0)
    tensors = {
        n: (rng.standard_normal(s) * 0.05).astype(np.float32)
        for n, s, _ in formats.tensor_plan(cfg)
    }
    path = str(tmp_path / "tiny.m")
    formats.save_model(path, cfg, tensors)

    # 1) the leaves reaching `put` are host-resident: numpy arrays, or (for
    # Q40 matmul weights) LAZY memmap-backed handles that decode per shard
    from dllama_tpu.models.formats import LazyQ40, LazyQ40Stack

    seen = {}

    def spy_put(name, leaf):
        seen[name] = leaf
        if isinstance(leaf, (LazyQ40, LazyQ40Stack)):
            leaf = leaf.eager()  # undecode-until-sharded is the strongest form
        for x in jax.tree.leaves(leaf):
            assert isinstance(x, np.ndarray), (name, type(x))
        return jax.tree.map(jnp.asarray, leaf)

    cfg2, hs = read_header(path)
    load_params(path, cfg2, hs, put=spy_put)
    assert "layers.wq" in seen and "wcls" in seen

    # 2) end-to-end: load_model on a tp mesh shards every matmul weight
    loaded = load_model(path, mesh="tp=4")
    wq = loaded.engine.params["layers"]["wq"]
    assert isinstance(wq, QTensor)
    shard = wq.packed.sharding.shard_shape(wq.packed.shape)
    assert shard[-1] == wq.packed.shape[-1] // 4  # out-dim split over tp=4

    ref = load_model(path, mesh=None)
    prompt = np.array([[5, 9, 2, 7]], dtype=np.int32)
    np.testing.assert_allclose(
        np.asarray(loaded.engine.prefill(prompt)),
        np.asarray(ref.engine.prefill(prompt)),
        atol=2e-4, rtol=1e-3,
    )


def test_engine_sync_q80_matches_within_quantization_noise():
    """VERDICT r1 #9: `--sync q80` routes the wo/w2 partial exchange through
    the Q80 shard_map collective at runtime; logits stay within the Q80
    quantization-noise envelope of the bf16-sync engine and greedy decode
    picks the same tokens on this config."""
    params = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)
    prompt = np.array([[5, 9, 2, 7, 1, 3]], dtype=np.int32)

    ref = InferenceEngine(CFG, params, cache_dtype=jnp.float32)
    ref_logits = np.asarray(ref.prefill(prompt))

    mesh = make_mesh(MeshConfig(tp=4))
    sh = LlamaShardings(mesh, CFG)
    eng = InferenceEngine(CFG, params, cache_dtype=jnp.float32, shardings=sh, sync="q80")
    got = np.asarray(eng.prefill(prompt))
    # Q80 partial-sum exchange: ~1e-2 relative noise per layer, 2 layers
    np.testing.assert_allclose(got, ref_logits, atol=0.05, rtol=0.05)
    assert np.argmax(got, -1).tolist() == np.argmax(ref_logits, -1).tolist()

    ref_toks = ref.decode_greedy_n(np.array([[int(np.argmax(ref_logits))]]), 8)
    got_toks = eng.decode_greedy_n(np.array([[int(np.argmax(got))]]), 8)
    assert ref_toks.tolist() == got_toks.tolist()


def test_resolve_sync_policy():
    """'auto' encodes the COLLECTIVES.md recommendation — q80 only at tp=2
    (both byte accountings agree there), bf16 at tp>=4, on pp meshes, and
    unsharded; explicit choices always win; junk is rejected."""
    from dllama_tpu.parallel.collectives import resolve_sync
    from dllama_tpu.parallel.sharding import LlamaShardings

    sh = lambda **kw: LlamaShardings(make_mesh(MeshConfig(**kw)), CFG)
    assert resolve_sync("auto", None) == "bf16"
    assert resolve_sync("auto", sh(tp=2, dp=2)) == "q80"
    assert resolve_sync("auto", sh(tp=4)) == "bf16"
    assert resolve_sync("auto", sh(tp=2, pp=2)) == "bf16"
    assert resolve_sync("q80", sh(tp=4)) == "q80"  # explicit wins
    assert resolve_sync("bf16", sh(tp=2)) == "bf16"
    with pytest.raises(ValueError, match="sync"):
        resolve_sync("fp8", None)


def test_engine_sync_auto_quantizes_only_tp2():
    """An engine built with sync='auto' arms the q80 col_fn exactly when the
    policy says q80 (tp=2) and stays on native collectives at tp=4."""
    params = random_params(CFG, seed=3, dtype=jnp.float32, quantize=False)
    eng2 = InferenceEngine(CFG, params, cache_dtype=jnp.float32,
                           shardings=LlamaShardings(make_mesh(MeshConfig(tp=2, dp=2)), CFG),
                           sync="auto")
    eng4 = InferenceEngine(CFG, params, cache_dtype=jnp.float32,
                           shardings=LlamaShardings(make_mesh(MeshConfig(tp=4)), CFG),
                           sync="auto")
    assert eng2.sync == "q80" and eng4.sync == "bf16"


def test_uneven_vocab_replicates_instead_of_crashing(tmp_path):
    """A vocab that doesn't divide tp must load with wcls replicated (the
    reference refuses such configs outright; we sanitize the spec). Caught by
    driving the CLI with the odd-vocab golden fixture on a tp=2 mesh."""
    from dllama_tpu.engine.loader import load_model
    from dllama_tpu.models import formats
    from dllama_tpu.ops.quant import FloatType

    cfg = LlamaConfig(dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
                      vocab_size=129, seq_len=64, weight_type=FloatType.Q40)
    rng = np.random.default_rng(0)
    tensors = {n: (rng.standard_normal(s) * 0.05).astype(np.float32)
               for n, s, _ in formats.tensor_plan(cfg)}
    path = str(tmp_path / "odd.m")
    formats.save_model(path, cfg, tensors)

    loaded = load_model(path, mesh="tp=2")  # must not raise
    wcls = loaded.engine.params["wcls"]
    # replicated: every device holds the full (odd) vocab dim
    assert wcls.packed.sharding.shard_shape(wcls.packed.shape) == wcls.packed.shape
    ref = load_model(path, mesh=None)
    prompt = np.array([[5, 9, 2]], dtype=np.int32)
    np.testing.assert_allclose(
        np.asarray(loaded.engine.prefill(prompt)),
        np.asarray(ref.engine.prefill(prompt)), atol=2e-4, rtol=1e-3,
    )
