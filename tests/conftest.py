"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference tests multi-node behavior by spawning localhost worker processes
(examples/n-workers.sh); we do strictly better — every distributed test runs in
CI on a virtual 8-device mesh via XLA's host-platform device splitting
(SURVEY.md §4). Env vars must be set before jax initializes.
"""

import os

# Force CPU: the session environment pins JAX_PLATFORMS=axon (the real TPU
# tunnel); tests must not compete for the single chip. jax is pre-imported by
# a sitecustomize hook before this file runs, so the env var is captured too
# late — the config update below is the authoritative override. XLA_FLAGS is
# still read lazily at first backend init, so setting it here works.
os.environ["JAX_PLATFORMS"] = "cpu"
# Paged-KV allocator auditing after EVERY release (engine/batch.PagePool):
# any refcount/free-list corruption fails at the release that caused it,
# suite-wide, instead of surfacing as a mystery page leak later.
os.environ.setdefault("DLLAMA_POOL_AUDIT", "1")
# Runtime lock-order sanitizer (utils/locks, ISSUE 14): every named lock
# the stack creates audits its acquisition rank suite-wide — an
# out-of-rank nesting (the shape that deadlocks once two threads
# interleave) raises LockOrderError naming both hold sites, at the test
# that introduced it. Must be set before dllama_tpu.obs imports.
os.environ.setdefault("DLLAMA_LOCK_AUDIT", "1")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import logging

import jax
import numpy as np
import pytest

# Daemon threads (HTTP server handlers, scheduler workers) can emit a log
# record after pytest has closed the capture stream their handler is bound
# to; logging then prints a multi-line "--- Logging error ---" dump to
# stderr, which interleaves with the -q progress dots and corrupts the
# tier-1 DOTS_PASSED accounting. The records themselves are harmless
# teardown noise — drop the dump, keep the records.
logging.raiseExceptions = False

jax.config.update("jax_platforms", "cpu")

# This JAX build's default matmul precision is bf16-like even for f32 inputs
# (on every backend). Tests compare f32 numerics against torch/numpy, so force
# true-f32 dots; production uses bf16 activations where the default is exact.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


#: The ROADMAP tier-1 verify line is TIME-BUDGETED (870 s — the full suite
#: does not finish on this box), so order buys coverage: cheapest
#: tests-per-second first. _RUN_FIRST are the pure-host suites (no model
#: compile, sub-second tests); the unlisted middle keeps its alphabetical
#: order; _RUN_LAST are the interpret-mode kernel / virtual-mesh numerics
#: suites — minutes of pure emulation each, exercising code only a real TPU
#: runs natively — which spend whatever budget remains. Nothing is skipped
#: or deselected; an un-budgeted `pytest tests/` still runs everything,
#: just in this order.
_RUN_FIRST = (
    "test_tokenizer.py",
    "test_perf.py",
    "test_trace.py",
    "test_native.py",
    "test_converters.py",
    "test_launch.py",
)
_RUN_LAST = (
    "test_pipeline.py",
    "test_sharding.py",
    "test_ring_attention.py",
    "test_sharded_pallas.py",
    "test_pallas_kernels.py",
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the time-budgeted tier-1 run (-m 'not slow') — "
        "long drills whose coverage an un-budgeted `pytest tests/` keeps")


def pytest_collection_modifyitems(config, items):
    first = {name: i - len(_RUN_FIRST) for i, name in enumerate(_RUN_FIRST)}
    last = {name: i + 1 for i, name in enumerate(_RUN_LAST)}
    items.sort(key=lambda item: first.get(
        item.fspath.basename, last.get(item.fspath.basename, 0)))
