"""Numerical parity of the whole Llama forward pass vs HuggingFace transformers.

The reference validates kernels against f32 reference impls with calibrated
tolerances (nn-cpu-ops-test.cpp); we go further and validate the *entire
model graph* — including the .m file roundtrip, the converter's rope
permutation, GQA, rope scaling and the KV cache — against an independent
implementation (torch LlamaForCausalLM) on random weights.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from dllama_tpu.models import formats
from dllama_tpu.models.config import LlamaConfig, RopeType
from dllama_tpu.models.llama import KVCache, forward
from dllama_tpu.ops.layers import build_rope_cache
from dllama_tpu.ops.quant import FloatType
from dllama_tpu.tools.converter_core import hf_tensor_for


def make_hf_model(rope_scaling=None, n_kv_heads=2):
    torch.manual_seed(0)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        rope_scaling=rope_scaling,
        attention_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    return model, hf_cfg


def convert_to_m(tmp_path, model, hf_cfg, weight_type=FloatType.F32):
    from dllama_tpu.tools.converter_core import hf_config_to_llama

    sd = {k: v.detach().numpy().astype(np.float32) for k, v in model.state_dict().items()}
    cfg_dict = hf_cfg.to_dict()
    cfg_dict["model_type"] = "llama"
    cfg = hf_config_to_llama(cfg_dict, weight_type)
    tensors = {}
    for name, shape, ft in formats.tensor_plan(cfg):
        tensors[name] = hf_tensor_for(name, cfg, lambda n: sd[n])
    path = str(tmp_path / "tiny.m")
    formats.save_model(path, cfg, tensors)
    return path


@pytest.mark.parametrize("scaling", [None, "llama3"])
def test_forward_matches_hf(tmp_path, scaling):
    rope_scaling = None
    if scaling == "llama3":
        rope_scaling = {
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        }
    model, hf_cfg = make_hf_model(rope_scaling)
    path = convert_to_m(tmp_path, model, hf_cfg)

    cfg, header_size = formats.read_header(path)
    assert cfg.dim == 64 and cfg.n_layers == 2 and cfg.n_kv_heads == 2
    if scaling == "llama3":
        assert cfg.rope_type == RopeType.LLAMA3_1
    params = formats.load_params(path, cfg, header_size, dtype=jnp.float32)

    tokens = np.array([[1, 5, 9, 200, 3, 17, 42, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()

    cache = KVCache.create(cfg, batch=1, dtype=jnp.float32)
    rope = build_rope_cache(cfg)
    logits, cache = forward(cfg, params, jnp.asarray(tokens), jnp.int32(0), cache, rope)
    np.testing.assert_allclose(np.asarray(logits), hf_logits, atol=2e-4, rtol=2e-3)


def test_incremental_decode_matches_full_prefill(tmp_path):
    """Token-by-token decode through the KV cache == one-shot prefill."""
    model, hf_cfg = make_hf_model()
    path = convert_to_m(tmp_path, model, hf_cfg)
    cfg, header_size = formats.read_header(path)
    params = formats.load_params(path, cfg, header_size, dtype=jnp.float32)
    rope = build_rope_cache(cfg)

    tokens = np.array([[1, 5, 9, 200, 3, 17]], dtype=np.int32)
    cache = KVCache.create(cfg, batch=1, dtype=jnp.float32)
    full_logits, _ = forward(cfg, params, jnp.asarray(tokens), jnp.int32(0), cache, rope)

    cache = KVCache.create(cfg, batch=1, dtype=jnp.float32)
    step_logits = []
    for i in range(tokens.shape[1]):
        lg, cache = forward(cfg, params, jnp.asarray(tokens[:, i : i + 1]), jnp.int32(i), cache, rope)
        step_logits.append(np.asarray(lg)[:, 0])
    step_logits = np.stack(step_logits, axis=1)
    np.testing.assert_allclose(step_logits, np.asarray(full_logits), atol=1e-4, rtol=1e-3)


def test_q40_model_close_to_f32(tmp_path):
    """Q40-quantized weights stay within quantization-noise distance of f32
    logits (the moral equivalent of matmul_Q80_Q40 vs F32 eps=4.0 in
    nn-cpu-ops-test.cpp:228-232, at model scale)."""
    model, hf_cfg = make_hf_model()
    path32 = convert_to_m(tmp_path, model, hf_cfg, FloatType.F32)
    import dllama_tpu.tools.converter_core as cc

    sd = {k: v.detach().numpy().astype(np.float32) for k, v in model.state_dict().items()}
    cfg_dict = hf_cfg.to_dict()
    cfg_dict["model_type"] = "llama"
    cfg40 = cc.hf_config_to_llama(cfg_dict, FloatType.Q40)
    tensors = {
        name: hf_tensor_for(name, cfg40, lambda n: sd[n])
        for name, shape, ft in formats.tensor_plan(cfg40)
    }
    path40 = str(tmp_path / "tiny_q40.m")
    formats.save_model(path40, cfg40, tensors)

    cfg32, hs32 = formats.read_header(path32)
    cfg40, hs40 = formats.read_header(path40)
    p32 = formats.load_params(path32, cfg32, hs32, dtype=jnp.float32)
    p40 = formats.load_params(path40, cfg40, hs40, dtype=jnp.float32)

    tokens = jnp.asarray(np.array([[1, 5, 9, 200]], dtype=np.int32))
    rope = build_rope_cache(cfg32)
    lg32, _ = forward(cfg32, p32, tokens, jnp.int32(0), KVCache.create(cfg32, 1, jnp.float32), rope)
    lg40, _ = forward(cfg40, p40, tokens, jnp.int32(0), KVCache.create(cfg40, 1, jnp.float32), rope)
    # random 0.02-scale weights -> tiny logits; compare correlation + abs error
    a, b = np.asarray(lg32).ravel(), np.asarray(lg40).ravel()
    assert np.corrcoef(a, b)[0, 1] > 0.98
