"""Converter CLI tests: HF safetensors -> .m, Meta .pth -> .m, tokenizers -> .t.

The .m converters are validated end-to-end: synthesize a checkpoint on disk,
run the converter, reload with the engine loader, and compare logits against
a torch-free reference path (the same parity harness test_model_parity uses).
"""

import base64
import json
import os
import struct

import numpy as np
import pytest

from dllama_tpu.models import formats
from dllama_tpu.models.config import LlamaConfig
from dllama_tpu.tokenizer.tokenizer import Tokenizer
from dllama_tpu.tools import convert_tokenizer
from dllama_tpu.tools.convert_hf import convert_hf
from dllama_tpu.tools.convert_llama import convert_llama
from dllama_tpu.tools.converter_core import hf_tensor_for, permute_rope

DIM, HIDDEN, LAYERS, HEADS, KV, VOCAB, SEQ = 16, 32, 2, 4, 2, 64, 32


def tiny_hf_config():
    return {
        "model_type": "llama",
        "hidden_act": "silu",
        "hidden_size": DIM,
        "intermediate_size": HIDDEN,
        "num_hidden_layers": LAYERS,
        "num_attention_heads": HEADS,
        "num_key_value_heads": KV,
        "max_position_embeddings": SEQ,
        "vocab_size": VOCAB,
        "rms_norm_eps": 1e-5,
        "rope_theta": 10000.0,
    }


def tiny_hf_tensors(rng):
    kv_dim = DIM * KV // HEADS
    t = {
        "model.embed_tokens.weight": rng.standard_normal((VOCAB, DIM)),
        "model.norm.weight": rng.standard_normal((DIM,)),
        "lm_head.weight": rng.standard_normal((VOCAB, DIM)),
    }
    for l in range(LAYERS):
        p = f"model.layers.{l}."
        t[p + "self_attn.q_proj.weight"] = rng.standard_normal((DIM, DIM))
        t[p + "self_attn.k_proj.weight"] = rng.standard_normal((kv_dim, DIM))
        t[p + "self_attn.v_proj.weight"] = rng.standard_normal((kv_dim, DIM))
        t[p + "self_attn.o_proj.weight"] = rng.standard_normal((DIM, DIM))
        t[p + "mlp.gate_proj.weight"] = rng.standard_normal((HIDDEN, DIM))
        t[p + "mlp.down_proj.weight"] = rng.standard_normal((DIM, HIDDEN))
        t[p + "mlp.up_proj.weight"] = rng.standard_normal((HIDDEN, DIM))
        t[p + "input_layernorm.weight"] = rng.standard_normal((DIM,))
        t[p + "post_attention_layernorm.weight"] = rng.standard_normal((DIM,))
    return {k: v.astype(np.float32) for k, v in t.items()}


def write_hf_checkpoint(tmp_path, tensors, sharded=False):
    from safetensors.numpy import save_file

    model_dir = tmp_path / "hf_model"
    model_dir.mkdir(exist_ok=True)
    with open(model_dir / "config.json", "w") as f:
        json.dump(tiny_hf_config(), f)
    if sharded:
        names = sorted(tensors)
        half = len(names) // 2
        shards = {"model-1.safetensors": names[:half], "model-2.safetensors": names[half:]}
        weight_map = {}
        for fn, keys in shards.items():
            save_file({k: tensors[k] for k in keys}, str(model_dir / fn))
            weight_map.update({k: fn for k in keys})
        with open(model_dir / "model.safetensors.index.json", "w") as f:
            json.dump({"weight_map": weight_map}, f)
    else:
        save_file(tensors, str(model_dir / "model.safetensors"))
    return str(model_dir)


@pytest.mark.parametrize("sharded", [False, True])
def test_convert_hf_roundtrip(tmp_path, rng, sharded):
    tensors = tiny_hf_tensors(rng)
    model_dir = write_hf_checkpoint(tmp_path, tensors, sharded=sharded)
    out = str(tmp_path / "model.m")
    convert_hf(model_dir, "f32", out)

    cfg, header_size = formats.read_header(out)
    assert (cfg.dim, cfg.n_layers, cfg.vocab_size) == (DIM, LAYERS, VOCAB)
    for name, shape, ft, raw in formats.iter_tensors(out, cfg, header_size):
        got = formats.decode_dense(raw, shape, ft)
        want = hf_tensor_for(name, cfg, lambda k: tensors[k])
        np.testing.assert_allclose(got, want, rtol=0, atol=0, err_msg=name)


def test_convert_hf_tied_embeddings(tmp_path, rng):
    tensors = tiny_hf_tensors(rng)
    del tensors["lm_head.weight"]  # tied: wcls falls back to embed_tokens
    model_dir = write_hf_checkpoint(tmp_path, tensors)
    out = str(tmp_path / "tied.m")
    convert_hf(model_dir, "f32", out)
    cfg, header_size = formats.read_header(out)
    for name, shape, ft, raw in formats.iter_tensors(out, cfg, header_size):
        if name == "wcls":
            np.testing.assert_array_equal(
                formats.decode_dense(raw, shape, ft), tensors["model.embed_tokens.weight"]
            )


def test_permute_rope_matches_rotate_half_semantics():
    # A [heads*hd, in] matrix whose row r is one-hot at r lets us read the
    # permutation directly: row i of the permuted matrix must be source row
    # pair-interleave(i) within its head block.
    hd = DIM // HEADS
    eye = np.eye(DIM, dtype=np.float32)
    p = permute_rope(eye, HEADS)
    for h in range(HEADS):
        for i in range(hd // 2):
            np.testing.assert_array_equal(p[h * hd + 2 * i], eye[h * hd + i])
            np.testing.assert_array_equal(p[h * hd + 2 * i + 1], eye[h * hd + hd // 2 + i])


# ------------------------------------------------------------------ meta


def test_convert_llama_meta_shards(tmp_path, rng):
    torch = pytest.importorskip("torch")
    kv_dim = DIM * KV // HEADS
    full = {
        "tok_embeddings.weight": rng.standard_normal((VOCAB, DIM)),
        "norm.weight": rng.standard_normal((DIM,)),
        "output.weight": rng.standard_normal((VOCAB, DIM)),
    }
    for l in range(LAYERS):
        p = f"layers.{l}."
        full[p + "attention.wq.weight"] = rng.standard_normal((DIM, DIM))
        full[p + "attention.wk.weight"] = rng.standard_normal((kv_dim, DIM))
        full[p + "attention.wv.weight"] = rng.standard_normal((kv_dim, DIM))
        full[p + "attention.wo.weight"] = rng.standard_normal((DIM, DIM))
        full[p + "feed_forward.w1.weight"] = rng.standard_normal((HIDDEN, DIM))
        full[p + "feed_forward.w2.weight"] = rng.standard_normal((DIM, HIDDEN))
        full[p + "feed_forward.w3.weight"] = rng.standard_normal((HIDDEN, DIM))
        full[p + "attention_norm.weight"] = rng.standard_normal((DIM,))
        full[p + "ffn_norm.weight"] = rng.standard_normal((DIM,))
    full = {k: v.astype(np.float32) for k, v in full.items()}

    # split into 2 megatron-style shards: wo/w2/embeddings on dim 1, rest dim 0
    model_dir = tmp_path / "meta_model"
    model_dir.mkdir()
    axis1 = ("tok_embeddings.weight", "attention.wo.weight", "feed_forward.w2.weight")
    for s in range(2):
        shard = {}
        for k, v in full.items():
            if v.ndim == 1:
                shard[k] = torch.tensor(v)
            else:
                ax = 1 if any(k == a or k.endswith(a) for a in axis1) else 0
                shard[k] = torch.tensor(np.split(v, 2, axis=ax)[s])
        torch.save(shard, str(model_dir / f"consolidated.0{s}.pth"))
    with open(model_dir / "params.json", "w") as f:
        json.dump({"dim": DIM, "n_layers": LAYERS, "n_heads": HEADS, "n_kv_heads": KV,
                   "vocab_size": VOCAB, "max_seq_len": SEQ, "norm_eps": 1e-5,
                   "rope_theta": 10000.0}, f)

    out = str(tmp_path / "meta.m")
    convert_llama(str(model_dir), "f32", out)
    cfg, header_size = formats.read_header(out)
    assert cfg.hidden_dim == HIDDEN  # derived from w1 shard rows * n_shards
    name_map = {
        "embedding": "tok_embeddings.weight", "final_norm": "norm.weight", "wcls": "output.weight",
        "wq": "attention.wq.weight", "wk": "attention.wk.weight", "wv": "attention.wv.weight",
        "wo": "attention.wo.weight", "w1": "feed_forward.w1.weight",
        "w2": "feed_forward.w2.weight", "w3": "feed_forward.w3.weight",
        "rms_att": "attention_norm.weight", "rms_ffn": "ffn_norm.weight",
    }
    for name, shape, ft, raw in formats.iter_tensors(out, cfg, header_size):
        parts = name.split(".")
        key = (f"layers.{parts[1]}." + name_map[parts[2]]) if len(parts) == 3 else name_map[name]
        np.testing.assert_allclose(formats.decode_dense(raw, shape, ft), full[key], err_msg=name)


# ------------------------------------------------------------------ tokenizers


def test_convert_hf_tokenizer(tmp_path):
    # Byte-level BPE over ascii: vocab = printable aliases for bytes + merges.
    enc = {b: c for c, b in convert_tokenizer.byte_decoder().items()}
    base = [enc[b] for b in range(256)]
    merges = [f"{enc[ord('h')]} {enc[ord('i')]}"]  # "hi" merge
    vocab = {tok: i for i, tok in enumerate(base)}
    vocab[enc[ord("h")] + enc[ord("i")]] = len(vocab)
    bos, eos = len(vocab), len(vocab) + 1
    tok_json = {
        "model": {"type": "BPE", "vocab": vocab, "merges": merges},
        "added_tokens": [
            {"id": bos, "content": "<s>"},
            {"id": eos, "content": "</s>"},
        ],
    }
    d = tmp_path / "hftok"
    d.mkdir()
    with open(d / "tokenizer.json", "w") as f:
        json.dump(tok_json, f)
    with open(d / "tokenizer_config.json", "w") as f:
        json.dump({"bos_token": "<s>", "eos_token": "</s>", "chat_template": "T"}, f)

    tok = convert_tokenizer.convert_hf_tokenizer(str(d))
    assert tok.bos_id == bos and tok.eos_ids == [eos]
    assert tok.vocab[vocab[enc[ord("h")] + enc[ord("i")]]] == b"hi"
    # merge must win over single bytes (score -id: merged id > byte ids, but
    # encode picks the *mergeable pair* with the highest score among candidates;
    # "hi" is the only candidate so it merges).
    ids = tok.encode("hi", add_bos=False)
    assert ids == [vocab[enc[ord("h")] + enc[ord("i")]]]

    path = str(tmp_path / "hf.t")
    tok.save(path)
    tok2 = Tokenizer.load(path)
    assert tok2.vocab == tok.vocab and tok2.chat_template == "T"


def test_convert_hf_tokenizer_list_eos(tmp_path):
    # Llama-3.1-style config.json with "eos_token_id": [a, b, c] and no
    # bos/eos strings in tokenizer_config.json.
    enc = {b: c for c, b in convert_tokenizer.byte_decoder().items()}
    vocab = {enc[b]: b for b in range(256)}
    bos, e0, e1 = 256, 257, 258
    tok_json = {
        "model": {"type": "BPE", "vocab": vocab, "merges": []},
        "added_tokens": [
            {"id": bos, "content": "<bot>"},
            {"id": e0, "content": "<eot0>"},
            {"id": e1, "content": "<eot1>"},
        ],
    }
    d = tmp_path / "hftok31"
    d.mkdir()
    with open(d / "tokenizer.json", "w") as f:
        json.dump(tok_json, f)
    with open(d / "config.json", "w") as f:
        json.dump({"bos_token_id": bos, "eos_token_id": [e0, e1]}, f)
    tok = convert_tokenizer.convert_hf_tokenizer(str(d))
    assert tok.bos_id == bos and tok.eos_ids == [e0, e1]
    tok.save(str(tmp_path / "l31.t"))  # must not TypeError
    assert Tokenizer.load(str(tmp_path / "l31.t")).eos_ids == [e0, e1]


def test_parse_sentencepiece_model(tmp_path):
    # Hand-encode a sentencepiece ModelProto: repeated field 1, each message
    # {1: piece bytes, 2: float score, 3: varint type}.
    def sp_piece(piece: bytes, score: float, ptype: int = 1) -> bytes:
        body = bytes([0x0A, len(piece)]) + piece  # field 1, wire 2
        body += b"\x15" + struct.pack("<f", score)  # field 2, wire 5
        body += bytes([0x18, ptype])  # field 3, wire 0
        return bytes([0x0A, len(body)]) + body  # outer field 1, wire 2

    pieces = [(b"<unk>", 0.0, 2), (b"<s>", 0.0, 3), (b"</s>", 0.0, 3),
              ("▁hello".encode(), -1.5, 1), (b"x", -2.25, 1),
              (b"<0x0A>", -3.0, 6), (b"<0x68>", -3.5, 6)]
    blob = b"".join(sp_piece(p, s, t) for p, s, t in pieces)
    # trailing unknown field (trainer_spec, field 2 wire 2) must be skipped
    blob += bytes([0x12, 3]) + b"abc"
    d = tmp_path / "sptok"
    d.mkdir()
    with open(d / "tokenizer.model", "wb") as f:
        f.write(blob)

    parsed = convert_tokenizer.parse_sentencepiece_model(str(d / "tokenizer.model"))
    assert [p for p, _, _ in parsed] == [
        "<unk>", "<s>", "</s>", "▁hello", "x", "<0x0A>", "<0x68>"
    ]
    assert parsed[3][1] == -1.5 and parsed[0][2] == 2 and parsed[5][2] == 6

    tok = convert_tokenizer.convert_llama2_tokenizer(str(d))
    assert tok.vocab[3] == b" hello" and tok.bos_id == 1
    # BYTE fallback pieces become raw bytes in the merge vocabulary, so any
    # byte sequence tokenizes (the '<0x0A>' literal-string bug regression)
    assert tok.vocab[5] == b"\n" and tok.vocab[6] == b"h"
    assert tok.encode("h\n", add_bos=False) == [6, 5]
    # control/unknown pieces are special, not merge candidates
    assert tok.regular_vocab_size == len(tok.vocab) - 3


def test_convert_llama3_tokenizer(tmp_path):
    lines = [f"{base64.b64encode(bytes([i])).decode()} {i}" for i in range(64)]
    path = tmp_path / "tokenizer.model"
    path.write_text("\n".join(lines) + "\n")
    tok = convert_tokenizer.convert_llama3_tokenizer(str(path))
    assert len(tok.vocab) == 64 + 256
    assert tok.bos_id == 64 and tok.vocab[64] == b"<|begin_of_text|>"
    assert tok.eos_ids == [65, 64 + 9] and tok.vocab[73] == b"<|eot_id|>"
    assert tok.regular_vocab_size == 64


def test_convert_tokenizer_cli(tmp_path, monkeypatch):
    lines = [f"{base64.b64encode(bytes([i])).decode()} {i}" for i in range(16)]
    model = tmp_path / "tokenizer.model"
    model.write_text("\n".join(lines) + "\n")
    monkeypatch.chdir(tmp_path)
    assert convert_tokenizer.main(["llama3", str(model), "--name", "test"]) == 0
    tok = Tokenizer.load(str(tmp_path / "dllama_tokenizer_test.t"))
    assert len(tok.vocab) == 16 + 256


def test_convert_hf_tokenizer_metaspace_style(tmp_path):
    """Mistral/Llama-2-HF layout: BPE tokenizer.json with Metaspace + byte
    fallback and specials at the *head* — must not go through the GPT-2 byte
    decoder, and the head specials must not truncate the merge vocabulary."""
    vocab = {"<unk>": 0, "<s>": 1, "</s>": 2, "<0x0A>": 3, "h": 4, "i": 5,
             "▁": 6, "hi": 7, "▁hi": 8}
    tok_json = {
        "model": {"type": "BPE", "vocab": vocab, "merges": ["h i", "▁ hi"]},
        "pre_tokenizer": {"type": "Metaspace"},
        "decoder": {"type": "Sequence", "decoders": [
            {"type": "Replace"}, {"type": "ByteFallback"}]},
        "added_tokens": [
            {"id": 0, "content": "<unk>"},
            {"id": 1, "content": "<s>"},
            {"id": 2, "content": "</s>"},
        ],
    }
    d = tmp_path / "mstok"
    d.mkdir()
    with open(d / "tokenizer.json", "w") as f:
        json.dump(tok_json, f)
    with open(d / "tokenizer_config.json", "w") as f:
        json.dump({"bos_token": "<s>", "eos_token": "</s>"}, f)

    tok = convert_tokenizer.convert_hf_tokenizer(str(d))
    assert tok.bos_id == 1 and tok.eos_ids == [2]
    assert tok.vocab[6] == b" " and tok.vocab[8] == b" hi"  # metaspace -> space
    assert tok.vocab[3] == b"\n"  # byte fallback -> raw byte
    # head specials stay special; the rest is mergeable
    assert tok.regular_vocab_size == len(tok.vocab) - 3
    assert tok.encode(" hi", add_bos=False) == [8]
    assert tok.encode("hi\n", add_bos=False) == [7, 3]
